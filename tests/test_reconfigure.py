"""Epoch change / reconfiguration, mirroring
/root/reference/primary/tests/epoch_change.rs (in-band NewEpoch liveness) and
/root/reference/node/tests/reconfigure.rs (NodeRestarter-driven change)."""

import asyncio

import pytest

from narwhal_tpu.cluster import Cluster
from narwhal_tpu.messages import ReconfigureMsg
from narwhal_tpu.network import NetworkClient


async def _wait_epoch_progress(cluster, epoch, min_round, timeout=30.0):
    """Wait until every running primary holds a certificate of `epoch` at or
    past `min_round` (the reference's rx_new_certificates round-10 wait)."""
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        done = 0
        for a in cluster.authorities:
            if a.primary is None:
                continue
            store = a.primary.storage.certificate_store
            certs = store.after_round(max(1, min_round))
            if any(c.epoch == epoch and c.round >= min_round for c in certs):
                done += 1
        running = sum(1 for a in cluster.authorities if a.primary is not None)
        if done == running:
            return
        if asyncio.get_event_loop().time() > deadline:
            raise AssertionError(
                f"epoch {epoch} never reached round {min_round} on all nodes "
                f"({done}/{running})"
            )
        await asyncio.sleep(0.1)


def test_in_band_epoch_change(run):
    """Send NewEpoch reconfigure messages to every primary: the whole
    committee must restart its DAG in the new epoch and keep producing
    certificates (epoch_change.rs simple_epoch_change)."""

    async def scenario():
        from narwhal_tpu.network import Credentials, committee_resolver

        cluster = Cluster(size=4, workers=1)
        await cluster.start()
        # Reconfigure is worker->primary control plane: each primary only
        # accepts it from its own workers, so impersonate each authority's
        # worker 0 (the reference app drives it through the worker,
        # state_handler.rs:100-172).
        clients = [
            NetworkClient(
                credentials=Credentials(
                    fixture_auth.worker_keypairs[0],
                    committee_resolver(
                        lambda: cluster.committee, lambda: cluster.worker_cache
                    ),
                )
            )
            for fixture_auth in cluster.fixture.authorities
        ]
        try:
            await cluster.assert_progress(commit_threshold=2, timeout=30.0)
            for epoch in (1, 2):
                new_committee = cluster.committee.to_json()
                import json

                doc = json.loads(new_committee)
                doc["epoch"] = epoch
                msg = ReconfigureMsg("new_epoch", json.dumps(doc))
                for a, client in zip(cluster.authorities, clients):
                    assert await client.unreliable_send(a.primary.address, msg)
                await _wait_epoch_progress(cluster, epoch, 6, timeout=30.0)
        finally:
            for client in clients:
                client.close()
            await cluster.shutdown()

    run(scenario(), timeout=120.0)


def test_worker_scale_out(run):
    """Two workers per authority: both lanes carry batches into headers and
    the committee commits transactions submitted to distinct lanes
    (SURVEY §2.14 worker sharding)."""

    async def scenario():
        from narwhal_tpu.messages import SubmitTransactionStreamMsg

        cluster = Cluster(size=4, workers=2)
        await cluster.start()
        client = NetworkClient()
        try:
            for wid in (0, 1):
                target = cluster.authorities[0].worker_transactions_address(wid)
                txs = tuple(bytes([wid]) * 24 + bytes([i]) for i in range(16))
                await client.request(target, SubmitTransactionStreamMsg(txs))

            got = []
            details = cluster.authorities[1]
            while len(got) < 32:
                _, tx = await asyncio.wait_for(
                    details.primary.tx_execution_output.recv(), 30.0
                )
                got.append(tx)
            # transactions from both worker lanes were ordered and executed
            assert any(tx[0] == 0 for tx in got) and any(tx[0] == 1 for tx in got)
        finally:
            client.close()
            await cluster.shutdown()

    run(scenario(), timeout=90.0)


def test_partial_committee_change(run):
    """Epoch change to a committee where one authority is REPLACED by a
    fresh identity whose node never starts (epoch_change.rs
    partial committee change): the three surviving members still hold
    2f+1 stake and must keep producing certificates in the new epoch."""

    async def scenario():
        import json

        from narwhal_tpu.crypto import KeyPair
        from narwhal_tpu.network import Credentials, committee_resolver

        cluster = Cluster(size=4, workers=1)
        await cluster.start()
        clients = [
            NetworkClient(
                credentials=Credentials(
                    fixture_auth.worker_keypairs[0],
                    committee_resolver(
                        lambda: cluster.committee, lambda: cluster.worker_cache
                    ),
                )
            )
            for fixture_auth in cluster.fixture.authorities
        ]
        try:
            await cluster.assert_progress(commit_threshold=2, timeout=30.0)
            # Replace authority 3 with a brand-new identity (no node runs
            # for it) and advance the epoch.
            doc = json.loads(cluster.committee.to_json())
            old_pk = cluster.fixture.authorities[3].public.hex()
            entry = doc["authorities"].pop(old_pk)
            newcomer = KeyPair.generate()
            newcomer_net = KeyPair.generate()
            entry["network_key"] = newcomer_net.public.hex()
            doc["authorities"][newcomer.public.hex()] = entry
            doc["epoch"] = 1
            msg = ReconfigureMsg("new_epoch", json.dumps(doc))
            # Deliver to the three surviving primaries (the replaced node
            # is no longer in the new committee).
            for a, client in zip(cluster.authorities[:3], clients[:3]):
                assert await client.unreliable_send(a.primary.address, msg)
            await cluster.stop_node(3)
            # 75s: with one replaced authority that never starts, quorum in
            # the new committee needs ALL three survivors — one laggard
            # adopting the epoch late (1-core host, pure-Python crypto)
            # stalls the other two until it catches up.
            await _wait_epoch_progress(cluster, 1, 4, timeout=75.0)
        finally:
            for client in clients:
                client.close()
            await cluster.shutdown()

    run(scenario(), timeout=120.0)


def test_partial_committee_change_deterministic_simnet(monkeypatch):
    """Regression for the test_partial_committee_change contention flake:
    the SAME semantics (authority 3 replaced by a fresh identity whose node
    never starts, epoch bumped, three survivors must keep certifying) on
    the simnet virtual clock, where 1-core host contention cannot slow the
    survivors — a failure here is a protocol bug, never a laggard. The
    flight-recorder trace of the wall-clock flake is checked in at
    tests/artifacts/partial_committee_change_flight.json; this test pins
    the property that trace shows degrading (epoch adoption stalling the
    epoch-1 quorum) in an environment where only logic can break it."""
    import hashlib
    import json
    import random as _random

    from narwhal_tpu import tracing
    from narwhal_tpu.config import Parameters
    from narwhal_tpu.crypto import KeyPair
    from narwhal_tpu.network import (
        Credentials,
        auth as _auth,
        committee_resolver,
        transport,
    )
    from narwhal_tpu.simnet import LinkSpec, SimFabric, SimLoop
    from narwhal_tpu.simnet.cluster import SimCluster

    monkeypatch.setenv("NARWHAL_TRACE", "1")
    seed = 21
    loop = SimLoop()
    asyncio.set_event_loop(loop)
    fabric = SimFabric(seed=seed, default_link=LinkSpec(latency=0.002))
    transport.install(fabric)
    _random.seed(seed)
    entropy_state = [b"simnet" + seed.to_bytes(8, "big")]

    def seeded_entropy(n: int) -> bytes:
        out = b""
        while len(out) < n:
            entropy_state[0] = hashlib.sha256(entropy_state[0]).digest()
            out += entropy_state[0]
        return out[:n]

    prev_entropy = _auth.set_entropy(seeded_entropy)

    params = Parameters(
        max_header_delay=0.1,
        max_batch_delay=0.05,
        header_delay_floor=0.05,
        batch_delay_floor=0.02,
    )

    async def main():
        cluster = SimCluster(size=4, fabric=fabric, workers=1, parameters=params)
        await cluster.start()
        clients = []
        try:
            await _wait_epoch_progress(cluster, 0, 2, timeout=60.0)
            # Replace authority 3 with a brand-new identity (its node never
            # starts) and advance the epoch — the real test's exact edit.
            doc = json.loads(cluster.committee.to_json())
            entry = doc["authorities"].pop(cluster.fixture.authorities[3].public.hex())
            entry["network_key"] = KeyPair.generate().public.hex()
            doc["authorities"][KeyPair.generate().public.hex()] = entry
            doc["epoch"] = 1
            msg = ReconfigureMsg("new_epoch", json.dumps(doc))
            for i in range(3):
                client = NetworkClient(
                    credentials=Credentials(
                        cluster.fixture.authorities[i].worker_keypairs[0],
                        committee_resolver(
                            lambda: cluster.committee, lambda: cluster.worker_cache
                        ),
                    )
                )
                clients.append(client)
                assert await client.unreliable_send(
                    cluster.authorities[i].primary.address, msg, timeout=5.0
                )
            await cluster.crash_node(3)
            # Virtual seconds: generous and FREE — no host-load sensitivity.
            await _wait_epoch_progress(cluster, 1, 4, timeout=120.0)
            # The flight recorder saw the survivors' epoch-1 commit spans:
            # the waterfall evidence the wall-clock flake's artifact lacks
            # past the stall point.
            dumps = [
                cluster.authorities[i].primary.tracer.dump() for i in range(3)
            ]
            falls = tracing.waterfall(dumps)
            assert any(
                "commit" in v["stages"] and "certify" in v["stages"]
                for v in falls.values()
            )
            # Round progress and commit delivery race by a few virtual
            # instants: node 0 can hold epoch-1 round-4 certificates while
            # its consensus task is still queued in the same instant. Wait
            # (virtual seconds, free) instead of snapshotting immediately.
            deadline = asyncio.get_event_loop().time() + 60.0
            while not any(epoch == 1 for epoch, _, _ in cluster.commits[0]):
                assert (
                    asyncio.get_event_loop().time() < deadline
                ), "node 0 never committed in epoch 1"
                await asyncio.sleep(0.1)
        finally:
            for client in clients:
                client.close()
            await cluster.shutdown()

    try:
        loop.run_until_complete(asyncio.wait_for(main(), 600.0))
    finally:
        _auth.set_entropy(prev_entropy)
        transport.uninstall()
        for t in asyncio.all_tasks(loop):
            t.cancel()
        loop.run_until_complete(asyncio.sleep(0))
        loop.run_until_complete(loop.shutdown_asyncgens())
        asyncio.set_event_loop(None)
        loop.close()


def test_restart_into_new_committee_via_node_restarter(run):
    """NodeRestarter-driven epoch change (node/tests/reconfigure.rs,
    restarter.rs): every primary is torn down and respawned against the
    epoch-1 committee (fresh addresses, fresh per-epoch store) and the new
    committee commits from genesis."""

    async def scenario():
        from dataclasses import replace

        from narwhal_tpu.config import Authority, get_available_port
        from narwhal_tpu.fixtures import CommitteeFixture
        from narwhal_tpu.node import NodeRestarter

        f = CommitteeFixture(size=4, workers=1)
        params = replace(f.parameters, max_header_delay=0.05)
        committee0 = f.committee
        for pk, auth in committee0.authorities.items():
            committee0.authorities[pk] = replace(
                auth, primary_address=f"127.0.0.1:{get_available_port()}"
            )
        restarters = [
            NodeRestarter(
                a.keypair, f.worker_cache, params,
                network_keypair=a.network_keypair,
            )
            for a in f.authorities
        ]

        async def wait_commits(nodes, threshold, timeout=45.0):
            deadline = asyncio.get_event_loop().time() + timeout
            while True:
                rounds = [
                    n.registry.value("consensus_last_committed_round")
                    for n in nodes
                ]
                if all(r >= threshold for r in rounds):
                    return
                if asyncio.get_event_loop().time() > deadline:
                    raise AssertionError(f"no commits: {rounds}")
                await asyncio.sleep(0.1)

        nodes = []
        try:
            for r in restarters:
                nodes.append(await r.start(committee0))
            await wait_commits(nodes, 2)

            # Epoch 1: same identities, fresh addresses, epoch bumped.
            from narwhal_tpu.config import Committee

            committee1 = Committee(
                {
                    pk: replace(
                        auth, primary_address=f"127.0.0.1:{get_available_port()}"
                    )
                    for pk, auth in committee0.authorities.items()
                },
                epoch=1,
            )
            nodes = []
            for r in restarters:
                nodes.append(await r.restart(committee1))
            await wait_commits(nodes, 2)
            # The new epoch's certificates really are epoch-1.
            store = nodes[0].storage.certificate_store
            assert any(c.epoch == 1 for c in store.after_round(1))
        finally:
            for r in restarters:
                if r.node is not None:
                    await r.node.shutdown()

    run(scenario(), timeout=150.0)
