"""Epoch change / reconfiguration, mirroring
/root/reference/primary/tests/epoch_change.rs (in-band NewEpoch liveness) and
/root/reference/node/tests/reconfigure.rs (NodeRestarter-driven change)."""

import asyncio

import pytest

from narwhal_tpu.cluster import Cluster
from narwhal_tpu.messages import ReconfigureMsg
from narwhal_tpu.network import NetworkClient


async def _wait_epoch_progress(cluster, epoch, min_round, timeout=30.0):
    """Wait until every running primary holds a certificate of `epoch` at or
    past `min_round` (the reference's rx_new_certificates round-10 wait)."""
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        done = 0
        for a in cluster.authorities:
            if a.primary is None:
                continue
            store = a.primary.storage.certificate_store
            certs = store.after_round(max(1, min_round))
            if any(c.epoch == epoch and c.round >= min_round for c in certs):
                done += 1
        running = sum(1 for a in cluster.authorities if a.primary is not None)
        if done == running:
            return
        if asyncio.get_event_loop().time() > deadline:
            raise AssertionError(
                f"epoch {epoch} never reached round {min_round} on all nodes "
                f"({done}/{running})"
            )
        await asyncio.sleep(0.1)


def test_in_band_epoch_change(run):
    """Send NewEpoch reconfigure messages to every primary: the whole
    committee must restart its DAG in the new epoch and keep producing
    certificates (epoch_change.rs simple_epoch_change)."""

    async def scenario():
        from narwhal_tpu.network import Credentials, committee_resolver

        cluster = Cluster(size=4, workers=1)
        await cluster.start()
        # Reconfigure is worker->primary control plane: each primary only
        # accepts it from its own workers, so impersonate each authority's
        # worker 0 (the reference app drives it through the worker,
        # state_handler.rs:100-172).
        clients = [
            NetworkClient(
                credentials=Credentials(
                    fixture_auth.worker_keypairs[0],
                    committee_resolver(
                        lambda: cluster.committee, lambda: cluster.worker_cache
                    ),
                )
            )
            for fixture_auth in cluster.fixture.authorities
        ]
        try:
            await cluster.assert_progress(commit_threshold=2, timeout=30.0)
            for epoch in (1, 2):
                new_committee = cluster.committee.to_json()
                import json

                doc = json.loads(new_committee)
                doc["epoch"] = epoch
                msg = ReconfigureMsg("new_epoch", json.dumps(doc))
                for a, client in zip(cluster.authorities, clients):
                    assert await client.unreliable_send(a.primary.address, msg)
                await _wait_epoch_progress(cluster, epoch, 6, timeout=30.0)
        finally:
            for client in clients:
                client.close()
            await cluster.shutdown()

    run(scenario(), timeout=120.0)


def test_worker_scale_out(run):
    """Two workers per authority: both lanes carry batches into headers and
    the committee commits transactions submitted to distinct lanes
    (SURVEY §2.14 worker sharding)."""

    async def scenario():
        from narwhal_tpu.messages import SubmitTransactionStreamMsg

        cluster = Cluster(size=4, workers=2)
        await cluster.start()
        client = NetworkClient()
        try:
            for wid in (0, 1):
                target = cluster.authorities[0].worker_transactions_address(wid)
                txs = tuple(bytes([wid]) * 24 + bytes([i]) for i in range(16))
                await client.request(target, SubmitTransactionStreamMsg(txs))

            got = []
            details = cluster.authorities[1]
            while len(got) < 32:
                _, tx = await asyncio.wait_for(
                    details.primary.tx_execution_output.recv(), 30.0
                )
                got.append(tx)
            # transactions from both worker lanes were ordered and executed
            assert any(tx[0] == 0 for tx in got) and any(tx[0] == 1 for tx in got)
        finally:
            client.close()
            await cluster.shutdown()

    run(scenario(), timeout=90.0)
