"""Wire-format stability snapshot, mirroring
/root/reference/node/tests/formats.rs:5 + node/src/generate_format.rs: the
canonical encodings and digests of fixed objects must never drift silently.
If a format change is intentional, update the snapshots below in the same
commit and call it out in the message."""

from narwhal_tpu.fixtures import CommitteeFixture
from narwhal_tpu.types import Batch, Certificate

# Deterministic fixture: seeded keypairs => stable keys, digests, signatures
# are deterministic for ed25519 (RFC 8032).
F = CommitteeFixture(size=4, seed=0)


def test_batch_format_snapshot():
    b = Batch((b"alpha", b"beta"))
    assert b.to_bytes().hex() == (
        "02000000" "05000000" + b"alpha".hex() + "04000000" + b"beta".hex()
    )
    assert b.digest.hex() == (
        "8a208d6b5ef9b60be4f1892f4473263b7269acede8a87f0392d7e5b405be211a"
    )


def test_header_format_snapshot():
    h = F.header(author=0, round=1)
    assert h.digest.hex() == (
        "addfc7891231ba34c589408397e9eb24720e15a1b52a688b768e6b6b6bb5046e"
    )
    # author (32B raw) + round + epoch + empty payload map + 4 genesis parents
    wire = h.to_bytes()
    assert wire[:32] == h.author
    assert wire[32:40] == (1).to_bytes(8, "little")
    assert wire[40:48] == (0).to_bytes(8, "little")


def test_certificate_format_snapshot():
    gen = Certificate.genesis(F.committee)
    digests = sorted(c.digest.hex() for c in gen)
    assert digests[0] == (
        "00a62328a6f7077216d6b07d87ae074973adbecb3360df41116d047cfe8c2393"
    )
    cert = F.certificate(F.header(author=0, round=1))
    rt = Certificate.from_bytes(cert.to_bytes())
    assert rt == cert
