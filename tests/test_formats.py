"""Wire-format stability snapshot, mirroring
/root/reference/node/tests/formats.rs:5 + node/src/generate_format.rs: the
canonical encodings and digests of fixed objects must never drift silently.
If a format change is intentional, update the snapshots below in the same
commit and call it out in the message."""

from narwhal_tpu.fixtures import CommitteeFixture
from narwhal_tpu.types import Batch, Certificate

# Deterministic fixture: seeded keypairs => stable keys, digests, signatures
# are deterministic for ed25519 (RFC 8032). Digests are SHA-256 of the
# canonical encoding (see crypto.digest256).
F = CommitteeFixture(size=4, seed=0)


def test_batch_format_snapshot():
    b = Batch((b"alpha", b"beta"))
    assert b.to_bytes().hex() == (
        "02000000" "05000000" + b"alpha".hex() + "04000000" + b"beta".hex()
    )
    assert b.digest.hex() == (
        "5e380ce3c499b6767ae9351088e94e34eaaae7161502ece47e8a05cc7aaf3112"
    )


def test_header_format_snapshot():
    h = F.header(author=0, round=1)
    assert h.digest.hex() == (
        "bf3c6b646a0f4332d70ebf16eb86965f98b613f1a1a3a52ff8d3b94b64c531aa"
    )
    # author (32B raw) + round + epoch + empty payload map + 4 genesis parents
    wire = h.to_bytes()
    assert wire[:32] == h.author
    assert wire[32:40] == (1).to_bytes(8, "little")
    assert wire[40:48] == (0).to_bytes(8, "little")


def test_certificate_format_snapshot():
    gen = Certificate.genesis(F.committee)
    digests = sorted(c.digest.hex() for c in gen)
    assert digests[0] == (
        "44b0b7462bee58356162d1286f3fdf02426f4dda0f0d01d56e2dc0c6dad1207b"
    )
    cert = F.certificate(F.header(author=0, round=1))
    rt = Certificate.from_bytes(cert.to_bytes())
    assert rt == cert
