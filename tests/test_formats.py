"""Wire-format stability snapshot, mirroring
/root/reference/node/tests/formats.rs:5 + node/src/generate_format.rs: the
canonical encodings and digests of fixed objects must never drift silently.
If a format change is intentional, update the snapshots below in the same
commit and call it out in the message."""

from narwhal_tpu.fixtures import CommitteeFixture
from narwhal_tpu.types import Batch, Certificate

# Deterministic fixture: seeded keypairs => stable keys, digests, signatures
# are deterministic for ed25519 (RFC 8032). Digests are SHA-256 of the
# canonical encoding (see crypto.digest256).
F = CommitteeFixture(size=4, seed=0)


def test_batch_format_snapshot():
    b = Batch((b"alpha", b"beta"))
    assert b.to_bytes().hex() == (
        "02000000" "05000000" + b"alpha".hex() + "04000000" + b"beta".hex()
    )
    assert b.digest.hex() == (
        "5e380ce3c499b6767ae9351088e94e34eaaae7161502ece47e8a05cc7aaf3112"
    )


def test_header_format_snapshot():
    h = F.header(author=0, round=1)
    assert h.digest.hex() == (
        "bf3c6b646a0f4332d70ebf16eb86965f98b613f1a1a3a52ff8d3b94b64c531aa"
    )
    # author (32B raw) + round + epoch + empty payload map + 4 genesis parents
    wire = h.to_bytes()
    assert wire[:32] == h.author
    assert wire[32:40] == (1).to_bytes(8, "little")
    assert wire[40:48] == (0).to_bytes(8, "little")


def test_certificate_format_snapshot():
    gen = Certificate.genesis(F.committee)
    digests = sorted(c.digest.hex() for c in gen)
    assert digests[0] == (
        "44b0b7462bee58356162d1286f3fdf02426f4dda0f0d01d56e2dc0c6dad1207b"
    )
    cert = F.certificate(F.header(author=0, round=1))
    rt = Certificate.from_bytes(cert.to_bytes())
    assert rt == cert


def _golden_messages():
    """One deterministic instance of EVERY registered message (the full
    generate_format surface, node/src/generate_format.rs): changing any
    encoding — or forgetting to extend this table when adding a message —
    fails the snapshot test below."""
    from narwhal_tpu import messages as M

    d1, d2 = b"\x11" * 32, b"\x22" * 32
    pk = F.authorities[0].public
    header = F.header(author=0, round=1)
    vote = F.votes(header)[0]
    cert = F.certificate(header)
    return {
        M.Ack: M.Ack(),
        M.HeaderMsg: M.HeaderMsg(header),
        M.VoteMsg: M.VoteMsg(vote),
        M.CertificateMsg: M.CertificateMsg(cert),
        M.CertificateRefMsg: M.CertificateRefMsg.from_certificate(
            Certificate.compact_from_votes(
                header, cert.signers, cert.signatures
            )
        ),
        M.CertificatesRequest: M.CertificatesRequest((d1, d2), pk),
        M.CertificatesBatchRequest: M.CertificatesBatchRequest((d1,), pk),
        M.CertificatesBatchResponse: M.CertificatesBatchResponse(
            ((d1, None), (cert.digest, cert))
        ),
        M.CertificatesRangeRequest: M.CertificatesRangeRequest(1, 9, pk),
        M.CertificatesRangeResponse: M.CertificatesRangeResponse((d1, d2)),
        M.PayloadAvailabilityRequest: M.PayloadAvailabilityRequest((d1,), pk),
        M.PayloadAvailabilityResponse: M.PayloadAvailabilityResponse(
            ((d1, True), (d2, False))
        ),
        M.SynchronizeMsg: M.SynchronizeMsg((d1,), pk),
        M.CleanupMsg: M.CleanupMsg(7),
        M.RequestBatchMsg: M.RequestBatchMsg(d1),
        M.RequestBatchesMsg: M.RequestBatchesMsg((d1, d2)),
        M.DeleteBatchesMsg: M.DeleteBatchesMsg((d1, d2)),
        M.BackpressureMsg: M.BackpressureMsg.from_level(0.75),
        M.ReconfigureMsg: M.ReconfigureMsg("new_epoch", "{}"),
        M.OurBatchMsg: M.OurBatchMsg(d1, 0),
        M.OthersBatchMsg: M.OthersBatchMsg(d2, 1),
        M.RequestedBatchMsg: M.RequestedBatchMsg(d1, b"\x33" * 8, True),
        M.RequestedBatchesMsg: M.RequestedBatchesMsg(
            ((d1, True, b"\x33" * 8), (d2, False, b""))
        ),
        M.DeletedBatchesMsg: M.DeletedBatchesMsg((d1,)),
        M.WorkerErrorMsg: M.WorkerErrorMsg("boom"),
        M.WorkerBatchMsg: M.WorkerBatchMsg(Batch((b"tx",)).to_bytes()),
        M.WorkerBatchRequest: M.WorkerBatchRequest((d1,)),
        M.WorkerBatchResponse: M.WorkerBatchResponse((Batch((b"tx",)).to_bytes(),)),
        M.SubmitTransactionMsg: M.SubmitTransactionMsg(b"payload"),
        M.SubmitTransactionStreamMsg: M.SubmitTransactionStreamMsg((b"a", b"bb")),
        M.GetCollectionsRequest: M.GetCollectionsRequest((d1,)),
        M.GetCollectionsResponse: M.GetCollectionsResponse(
            ((d1, ((d2, (b"t1", b"t2")),), ""),)
        ),
        M.RemoveCollectionsRequest: M.RemoveCollectionsRequest((d1,)),
        M.ReadCausalRequest: M.ReadCausalRequest(d1),
        M.ReadCausalResponse: M.ReadCausalResponse((d1, d2)),
        M.RoundsRequest: M.RoundsRequest(pk),
        M.RoundsResponse: M.RoundsResponse(2, 11),
        M.NodeReadCausalRequest: M.NodeReadCausalRequest(pk, 4),
        M.NewNetworkInfoRequest: M.NewNetworkInfoRequest(0, ((pk, 1, "h:1"),)),
        M.GetPrimaryAddressRequest: M.GetPrimaryAddressRequest(),
        M.GetPrimaryAddressResponse: M.GetPrimaryAddressResponse("h:1"),
        M.NewEpochRequest: M.NewEpochRequest(1),
        M.RelayMsg: M.RelayMsg(pk, 3, 0, M.HeaderMsg.TAG, b"\x44" * 16),
        M.RelayAckMsg: M.RelayAckMsg(d1, pk),
        M.DeltaHeaderMsg: M.DeltaHeaderMsg(
            pk, 2, 0, d1, ((d2, 1),), (0, 2, 3), b"\x55" * 64
        ),
        M.HeaderResyncRequest: M.HeaderResyncRequest(d1, pk, 1, pk),
        M.HeaderResyncResponse: M.HeaderResyncResponse((header,)),
        M.CertificateDeltaMsg: M.CertificateDeltaMsg.from_certificate(cert),
        M.Relay2Msg: M.Relay2Msg(1, 3, 0, 2, b"\x66" * 16),
        M.RelayAck2Msg: M.RelayAck2Msg(d1, 2),
        M.Vote2Msg: M.Vote2Msg.from_vote(vote),
        M.TelemetryScrapeMsg: M.TelemetryScrapeMsg(),
        M.TelemetryScrapeResponse: M.TelemetryScrapeResponse(
            "# HELP x y\n# TYPE x counter\nx 1.0\n"
        ),
        M.FlightDumpMsg: M.FlightDumpMsg(256),
        M.FlightDumpResponse: M.FlightDumpResponse(b'{"node":"n0"}'),
    }


def test_full_registry_format_snapshot():
    """Golden wire bytes for every message tag (tests/snapshots/messages.json).
    Regenerate deliberately with REGEN_SNAPSHOTS=1 and review the diff."""
    import hashlib
    import json
    import os

    from narwhal_tpu.messages import REGISTRY, encode_message

    goldens = _golden_messages()
    missing = [cls.__name__ for cls in REGISTRY.values() if cls not in goldens]
    assert not missing, f"no golden instance for: {missing}"

    snap_path = os.path.join(os.path.dirname(__file__), "snapshots", "messages.json")
    current = {}
    for cls, msg in sorted(goldens.items(), key=lambda kv: kv[0].TAG):
        tag, body = encode_message(msg)
        current[f"{tag}:{cls.__name__}"] = hashlib.sha256(body).hexdigest()

    if os.environ.get("REGEN_SNAPSHOTS"):
        os.makedirs(os.path.dirname(snap_path), exist_ok=True)
        with open(snap_path, "w") as f:
            json.dump(current, f, indent=2, sort_keys=True)
    with open(snap_path) as f:
        golden = json.load(f)
    assert current == golden, (
        "wire format drift; regenerate with REGEN_SNAPSHOTS=1 only if the "
        "change is intentional"
    )


def test_frame_header_format_snapshot():
    """Golden bytes for the transport frame header (network/rpc.py `_pack`):
    `<len u32><kind u8><rid u64><tag u16><lane u8>` little-endian. The lane
    byte (pool lane multiplexing) was an ADD-ONLY change — everything
    before it is byte-identical to the pre-pool header, and plaintext
    legacy frames carry lane 0."""
    from narwhal_tpu.network.rpc import KIND_ONEWAY, KIND_REQ, _pack

    frame = _pack(KIND_REQ, 0x0102030405060708, 73, b"body", lane=3)
    assert frame == (
        b"\x04\x00\x00\x00"  # len u32 = 4
        b"\x00"  # kind u8 = KIND_REQ
        b"\x08\x07\x06\x05\x04\x03\x02\x01"  # rid u64
        b"\x49\x00"  # tag u16 = 73
        b"\x03"  # lane u8
        b"body"
    )
    # Default lane is 0 — the legacy single-role wire form.
    assert _pack(KIND_ONEWAY, 0, 9, b"")[-1:] == b"\x00"
    assert len(_pack(KIND_REQ, 0, 0, b"")) == 16  # header is 16 bytes
