"""Remote benchmark orchestration exercised end-to-end through
LocalConnection: the full install -> configure -> start -> clients -> stop ->
collect-logs flow runs against three simulated hosts on this machine, so the
SSH command surface (the `fab remote` analog) is tested without sshd."""

import os
import shutil

import pytest

from benchmark.remote import LocalConnection, RemoteBench


@pytest.mark.slow
def test_remote_bench_flow_on_local_connections(tmp_path):
    # Four simulated machines that all resolve to this one (distinct roots,
    # distinct port blocks via the per-node offset in configure()).
    hosts = [f"node{i}@127.0.0.1" for i in range(4)]
    roots = {h: str(tmp_path / h.split("@")[0]) for h in hosts}

    def factory(host):
        return LocalConnection(host, roots[host])

    bench = RemoteBench(
        hosts,
        workers=1,
        base_port=0,  # 0 => give every node an ephemeral block below
        connection_factory=factory,
        work_dir=str(tmp_path / "ctl"),
    )
    # Ephemeral port blocks per node (the hosts share this machine).
    from narwhal_tpu.config import get_available_port

    bench.base_port = get_available_port()

    try:
        bench.install()
        for host in hosts:
            assert os.path.isdir(
                os.path.join(roots[host], "narwhal-tpu", "narwhal_tpu")
            ), f"install did not unpack on {host}"

        cfg = bench.configure()
        assert len(cfg["committee"].authorities) == 4
        for i, host in enumerate(hosts):
            key_path = os.path.join(roots[host], "narwhal-tpu", "configs", "key.json")
            assert os.path.exists(key_path)

        # Generous duration: every spawned interpreter pays this
        # environment's heavyweight preload on a single shared core. The
        # test verifies ORCHESTRATION (install/configure/start/logs), so one
        # retry with a longer window absorbs transient host contention.
        parser = bench.run(rate=800, tx_size=128, duration=20)
        if parser.consensus_throughput()[0] <= 0:
            parser = bench.run(rate=800, tx_size=128, duration=35)
        if parser.consensus_throughput()[0] <= 0:
            # Full-suite runs on this 1-core host can contend hard enough
            # that two windows both miss; escalate once more.
            parser = bench.run(rate=800, tx_size=128, duration=60)
        result = parser.result()
        assert "Consensus TPS" in result
        if parser.to_dict()["consensus_tps"] <= 0:
            # This test verifies ORCHESTRATION (install/configure/start/log
            # collection/parsing), not host capacity. Under full-suite
            # contention commits may not land inside any window on a 1-core
            # host; the pipeline is still proven end-to-end if the collected
            # logs show the committee proposing headers.
            assert parser.proposals, (
                f"no headers proposed — orchestration failed: {result}"
            )
    finally:
        bench.stop()
        shutil.rmtree(str(tmp_path), ignore_errors=True)
