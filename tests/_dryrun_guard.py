"""Subprocess body of test_dryrun_pins_unsharded_dispatch.

Runs the driver dryrun pinned to the UPPER half of the CPU devices with a
spy on the module-level `chain_commit` kernel — the unsharded jitted
dispatch that library code (an unmeshed TpuBullshark, exactly what
`--dag-backend tpu` wires without `--dag-shards`) reaches through the
process-default device — and exits non-zero if any kernel output or
device-resident window tensor lands outside the pinned device list (the
MULTICHIP_r02/r04 failure class: module-level jits following the process
default backend instead of the dry run's pinned devices).

Executed in its own process: the spy run compiles a kernel set for a
non-default device, and XLA:CPU's compiler has crashed when that compile
landed on top of a long-lived suite process's accumulated state —
isolation keeps the guard deterministic either way. The dryrun's sharded
verifier leg is skipped here (its compile bill is minutes and its evidence
— sharded verdicts — is not what this guard checks; the in-suite
dryrun_multichip[8] run still pays it once).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

import __graft_entry__  # noqa: E402
import narwhal_tpu.tpu.dag_kernels as dk  # noqa: E402


def main() -> int:
    cpus = jax.devices("cpu")
    if len(cpus) < 8:
        print("SKIP: need 8 cpu devices")
        return 0
    allowed = set(cpus[4:8])
    placements = []

    orig_chain_commit = dk.chain_commit

    def spy(*args, **kwargs):
        out = orig_chain_commit(*args, **kwargs)
        for leaf in jax.tree_util.tree_leaves(out):
            try:
                placements.extend(leaf.devices())
            except (AttributeError, jax.errors.ConcretizationTypeError):
                pass  # tracer (the meshed leg re-jits through us): not a
                # concrete dispatch, placement is governed by in_shardings
        return out

    dk.chain_commit = spy
    # The sharded-verifier leg's multi-minute compile adds nothing to this
    # placement check; skip it (see module docstring).
    __graft_entry__._VERIFIER_LEG_RAN = True
    __graft_entry__.dryrun_multichip(4, devices=cpus[4:])

    # The unmeshed production engine: module-level chain_commit dispatch
    # over the DEVICE-RESIDENT window, under the same pin the dryrun uses.
    # This is the exact route `--dag-backend tpu` takes in a node whose
    # process default device is NOT the dryrun's — the r04 failure class.
    import random as _random

    from narwhal_tpu.consensus import ConsensusState
    from narwhal_tpu.fixtures import CommitteeFixture, make_certificates
    from narwhal_tpu.stores import NodeStorage
    from narwhal_tpu.tpu.dag_kernels import TpuBullshark
    from narwhal_tpu.types import Certificate

    with jax.default_device(cpus[4]):
        f = CommitteeFixture(size=4)
        genesis = {c.digest for c in Certificate.genesis(f.committee)}
        certs, _ = make_certificates(
            f.committee, 1, 8, genesis,
            failure_probability=0.0, rng=_random.Random(0),
        )
        engine = TpuBullshark(
            f.committee, NodeStorage(None).consensus_store, 50, prewarm=False
        )
        state = ConsensusState(Certificate.genesis(f.committee))
        index = 0
        committed = 0
        for c in certs:
            out = engine.process_certificate(state, index, c)
            index += len(out)
            committed += len(out)
        if committed == 0:
            print("FAIL: unmeshed engine never committed")
            return 1
        for arr in engine.win.device_view():
            placements.extend(arr.devices())

    if not placements:
        print("FAIL: the dry run never dispatched the module-level kernel")
        return 1
    outside = {str(d) for d in placements if d not in allowed}
    if outside:
        print(f"FAIL: dispatch landed outside the pinned device list: {outside}")
        return 1
    print("GUARD-OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
