"""Subprocess body of test_dryrun_pins_unsharded_dispatch.

Runs the driver dryrun pinned to the UPPER half of the CPU devices with
spies on every ed25519 kernel dispatch, and exits non-zero if any kernel
output lands outside the pinned device list (the MULTICHIP_r02/r04
failure class). Executed in its own process: the spy run compiles a full
kernel set for a non-default device, and XLA:CPU's compiler has crashed
when that compile landed on top of a long-lived suite process's
accumulated state — isolation keeps the guard deterministic either way.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

import __graft_entry__  # noqa: E402
import narwhal_tpu.tpu.ed25519 as ed  # noqa: E402


def main() -> int:
    cpus = jax.devices("cpu")
    if len(cpus) < 8:
        print("SKIP: need 8 cpu devices")
        return 0
    allowed = set(cpus[4:8])
    placements = []

    def spying(kernel):
        def spy(*args, **kwargs):
            out = kernel(*args, **kwargs)
            for leaf in jax.tree_util.tree_leaves(out):
                placements.extend(leaf.devices())
            return out

        # The mesh-sharded verifier re-jits kernel.__wrapped__ with
        # explicit in_shardings; keep that route intact (it is pinned by
        # construction — the spy watches the *unsharded* dispatch path).
        spy.__wrapped__ = kernel.__wrapped__
        return spy

    ed.verify_batch_kernel = spying(ed.verify_batch_kernel)
    ed.msm_accumulate_kernel = spying(ed.msm_accumulate_kernel)
    __graft_entry__.dryrun_multichip(4, devices=cpus[4:])
    if not placements:
        print("FAIL: the dry run's verifier leg never dispatched a kernel")
        return 1
    outside = {str(d) for d in placements if d not in allowed}
    if outside:
        print(f"FAIL: dispatch landed outside the pinned device list: {outside}")
        return 1
    print("GUARD-OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
