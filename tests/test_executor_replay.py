"""Executor exactly-once across crashes at every cursor position.

Mirrors /root/reference/executor/src/tests/ replay tests: the application
persists ExecutionIndices atomically with each transaction's effects; after a
crash anywhere — mid-batch, exactly on a batch boundary, or between
certificates — a restarted Core re-executes the same consensus output and
every transaction is applied exactly once.
"""

import asyncio

import pytest

from narwhal_tpu.channels import Channel
from narwhal_tpu.executor.core import ExecutorCore
from narwhal_tpu.executor.state import ExecutionIndices
from narwhal_tpu.executor import ExecutionState
from narwhal_tpu.fixtures import CommitteeFixture, mock_certificate
from narwhal_tpu.stores import NodeStorage
from narwhal_tpu.types import Batch, Certificate, ConsensusOutput


class Crash(Exception):
    pass


class JournalState(ExecutionState):
    """Applies transactions to an append-only journal, persisting the cursor
    atomically with each effect (the ExecutionState contract); can be armed
    to crash BEFORE applying the Nth call (a crash after persisting the
    previous transaction, i.e. at an arbitrary cursor position)."""

    def __init__(self):
        self.journal: list[bytes] = []
        self.indices = ExecutionIndices()
        self.crash_at: int | None = None
        self.calls = 0

    async def handle_consensus_transaction(self, output, indices, transaction):
        if self.crash_at is not None and self.calls >= self.crash_at:
            raise Crash()
        self.calls += 1
        # Atomic effect+cursor persistence.
        self.journal.append(bytes(transaction))
        self.indices = indices
        return b""

    async def load_execution_indices(self) -> ExecutionIndices:
        return self.indices


def _output(f: CommitteeFixture, payload: dict) -> ConsensusOutput:
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    cert = mock_certificate(f.committee, f.authorities[0].public, 1, genesis, payload)
    return ConsensusOutput(certificate=cert, consensus_index=0)


def _run(coro):
    return asyncio.run(asyncio.wait_for(coro, 30))


@pytest.mark.parametrize("crash_at", list(range(0, 7)))
def test_exactly_once_output_stream_across_crash_points(crash_at):
    """The burst-drain core's output channel is exactly-once too: results
    applied before a mid-batch crash are flushed (never lost in the burst
    buffer), and replay — which skips below the watermark — never re-emits
    them. The full stream after restart is each tx once, in order."""

    async def scenario():
        f = CommitteeFixture(size=4)
        batches = {
            b"\x01" * 32: Batch(tuple(b"a%d" % i for i in range(4))),
            b"\x02" * 32: Batch(tuple(b"b%d" % i for i in range(2))),
        }
        payload = {d: 0 for d in batches}
        output = _output(f, payload)
        expected = [b"a0", b"a1", b"a2", b"a3", b"b0", b"b1"]

        state = JournalState()
        storage = NodeStorage(None)
        tx_output = Channel(100)
        core = ExecutorCore(
            state,
            storage.temp_batch_store,
            rx_subscriber=Channel(10),
            tx_output=tx_output,
        )
        core.execution_indices = await state.load_execution_indices()
        state.crash_at = crash_at
        try:
            await core.execute_certificate(output, batches)
        except Crash:
            pass
        state.crash_at = None
        recovered = await state.load_execution_indices()
        if recovered.next_certificate_index <= output.consensus_index:
            core2 = ExecutorCore(
                state,
                storage.temp_batch_store,
                rx_subscriber=Channel(10),
                tx_output=tx_output,
            )
            core2.execution_indices = recovered
            await core2.execute_certificate(output, batches)
        assert state.journal == expected
        emitted = []
        while True:
            item = tx_output.try_recv()
            if item is None:
                break
            emitted.append(item[1])
        assert emitted == expected, f"crash at {crash_at}: outputs {emitted}"

    _run(scenario())


@pytest.mark.parametrize("crash_at", list(range(0, 7)))
def test_exactly_once_across_crash_points(crash_at):
    """Two batches (4 + 2 txs, ordered by digest): crash before the Nth
    transaction for every N — including N=4, the batch boundary — restart,
    replay, and require the journal to hold each tx exactly once, in order."""

    async def scenario():
        f = CommitteeFixture(size=4)
        batches = {
            b"\x01" * 32: Batch(tuple(b"a%d" % i for i in range(4))),
            b"\x02" * 32: Batch(tuple(b"b%d" % i for i in range(2))),
        }
        payload = {d: 0 for d in batches}
        output = _output(f, payload)
        expected = [b"a0", b"a1", b"a2", b"a3", b"b0", b"b1"]

        state = JournalState()
        storage = NodeStorage(None)
        core = ExecutorCore(
            state,
            storage.temp_batch_store,
            rx_subscriber=Channel(10),
            tx_output=None,
        )
        core.execution_indices = await state.load_execution_indices()
        state.crash_at = crash_at
        try:
            await core.execute_certificate(output, batches)
            assert crash_at >= len(expected), "must crash before completing"
        except Crash:
            pass
        assert state.journal == expected[:crash_at]

        # "Restart": fresh Core, cursor recovered from the application. The
        # replay layer (get_restored_consensus_output, executor/__init__)
        # only re-delivers certificates at or past the recovered certificate
        # cursor — a fully executed certificate is not replayed.
        state.crash_at = None
        recovered = await state.load_execution_indices()
        if recovered.next_certificate_index <= output.consensus_index:
            core2 = ExecutorCore(
                state,
                storage.temp_batch_store,
                rx_subscriber=Channel(10),
                tx_output=None,
            )
            core2.execution_indices = recovered
            await core2.execute_certificate(output, batches)
        assert state.journal == expected, (
            f"crash at {crash_at}: journal {state.journal}"
        )

    _run(scenario())
