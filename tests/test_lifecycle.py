"""System-lifecycle tests: NodeRestarter-driven epoch change, staggered-boot
liveness, and causal completion across a disk-backed restart.

Mirrors /root/reference/node/tests/reconfigure.rs:438 (restarter-driven
epoch change), primary/tests/nodes_bootstrapping_tests.rs:246 (staggered
starts), and primary/tests/causal_completion_tests.rs:13 (restart from disk
then read the causal history).
"""

import asyncio

import pytest

from narwhal_tpu.cluster import Cluster
from narwhal_tpu.config import Committee, get_available_port
from narwhal_tpu.fixtures import CommitteeFixture
from narwhal_tpu.messages import SubmitTransactionStreamMsg
from narwhal_tpu.network import NetworkClient
from narwhal_tpu.node import NodeRestarter
from narwhal_tpu.stores import NodeStorage


async def _wait_metric(nodes, name, minimum, timeout=60.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        values = [n.registry.value(name) for n in nodes]
        if all(v is not None and v >= minimum for v in values):
            return values
        if asyncio.get_event_loop().time() > deadline:
            raise AssertionError(f"{name} never reached {minimum}: {values}")
        await asyncio.sleep(0.1)


def test_node_restarter_epoch_change(run):
    """Every authority runs under a NodeRestarter; after progress in epoch 0
    the whole committee is torn down and respawned with an epoch-1 committee
    (fresh per-epoch stores) and must resume committing — the
    reference's NodeRestarter::watch flow (node/src/restarter.rs:18-)."""

    async def scenario():
        from dataclasses import replace

        f = CommitteeFixture(size=4)
        f.parameters = replace(
            f.parameters, max_header_delay=0.05, max_batch_delay=0.05
        )
        # Pre-assign real ports (primaries only; no workers needed for
        # empty-header progress).
        from narwhal_tpu.config import Authority

        for pk, auth in f.committee.authorities.items():
            f.committee.authorities[pk] = Authority(
                auth.stake,
                f"127.0.0.1:{get_available_port()}",
                auth.network_key,
            )
        restarters = [
            NodeRestarter(
                a.keypair,
                f.worker_cache,
                f.parameters,
                network_keypair=a.network_keypair,
            )
            for a in f.authorities
        ]
        nodes = [await r.start(f.committee) for r in restarters]
        try:
            await _wait_metric(nodes, "consensus_last_committed_round", 2)

            # Epoch change: same authorities and addresses, epoch 1.
            new_committee = Committee(dict(f.committee.authorities), epoch=1)
            nodes = [await r.restart(new_committee) for r in restarters]
            for n in nodes:
                assert n.committee.epoch == 1
            await _wait_metric(nodes, "consensus_last_committed_round", 2)
        finally:
            for r in restarters:
                if r.node is not None:
                    await r.node.shutdown()

    run(scenario(), timeout=90.0)


def test_staggered_boot_liveness(run):
    """Nodes boot one by one with delays (the last after the rest have been
    running): the committee must reach liveness once 2f+1 are up and include
    the late joiner (nodes_bootstrapping_tests.rs staggered starts)."""

    async def scenario():
        cluster = Cluster(size=4, workers=1)
        try:
            # Boot 3 of 4 with gaps; quorum is reached at the third.
            for i in range(3):
                await cluster.start_node(i)
                await asyncio.sleep(0.3)
            await cluster.assert_progress(
                expected_nodes=3, commit_threshold=2, timeout=30.0
            )
            # The straggler joins much later and must catch up and commit.
            await cluster.start_node(3)
            rounds = await cluster.assert_progress(commit_threshold=4, timeout=30.0)
            assert rounds[cluster.authorities[3].name] >= 4
        finally:
            await cluster.shutdown()

    run(scenario(), timeout=90.0)


def test_causal_completion_after_disk_restart(run):
    """Stop a node mid-run, restart it from its on-disk stores, and verify
    its certificate store still holds the full causal history of its latest
    certificate — parent links resolve all the way to genesis
    (causal_completion_tests.rs restart scenario)."""

    async def scenario():
        from narwhal_tpu.types import Certificate

        cluster = Cluster(size=4, workers=1, store_base=None)
        # Disk-backed stores for node 0 only.
        import tempfile

        tmp = tempfile.mkdtemp(prefix="narwhal-lifecycle-")
        cluster.store_base = tmp
        await cluster.start()
        client = NetworkClient()
        try:
            target = cluster.authorities[0].worker_transactions_address(0)
            txs = tuple(bytes([4]) * 16 + bytes([i]) for i in range(16))
            await client.request(target, SubmitTransactionStreamMsg(txs))
            await cluster.assert_progress(commit_threshold=3, timeout=30.0)

            await cluster.restart_node(0)
            rounds = await cluster.assert_progress(commit_threshold=5, timeout=30.0)
            assert rounds[cluster.authorities[0].name] >= 5

            # Causal completion from the restarted node's own store: walk
            # parents from its newest certificate down to genesis.
            store = cluster.authorities[0].primary.storage.certificate_store
            last_round = store.last_round()
            assert last_round >= 3
            genesis = {c.digest for c in Certificate.genesis(cluster.committee)}
            newest = store.after_round(last_round)[0]
            frontier = set(newest.header.parents)
            visited = 0
            while frontier and not (frontier <= genesis):
                nxt = set()
                for d in frontier:
                    if d in genesis:
                        continue
                    cert = store.read(d)
                    assert cert is not None, "causal hole after restart"
                    visited += 1
                    nxt |= cert.header.parents
                frontier = nxt
            assert visited >= 3  # walked through real history, not a stub
        finally:
            client.close()
            await cluster.shutdown()

    run(scenario(), timeout=120.0)


@pytest.mark.slow  # 7-node committee on a 1-core host with the pure-Python
# crypto fallback runs minutes and misses its progress windows under load
def test_larger_committee_with_two_faults(run):
    """Seven validators (f=2): the committee commits, then keeps committing
    with two nodes stopped — quorum math beyond the 4-node default
    (SURVEY §2.14 scale-out by committee)."""

    async def scenario():
        cluster = Cluster(size=7, workers=1)
        await cluster.start()
        try:
            await cluster.assert_progress(commit_threshold=2, timeout=60.0)
            await cluster.stop_node(6)
            await cluster.stop_node(5)
            # Baseline AFTER the faults land, so the +2 requirement can only
            # be satisfied by genuinely post-fault commits.
            before = max(
                a.metric("consensus_last_committed_round")
                for a in cluster.authorities
                if a.primary is not None
            )
            rounds = await cluster.assert_progress(
                expected_nodes=5, commit_threshold=int(before) + 2, timeout=60.0
            )
            assert len(rounds) == 5
        finally:
            await cluster.shutdown()

    run(scenario(), timeout=150.0)
