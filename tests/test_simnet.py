"""simnet: deterministic adversary & fault-simulation harness.

Every scenario here runs the REAL protocol stack (actors, wire framing,
handshakes, AEAD) over the in-memory fabric on a virtual-clock loop — no
sockets, no wall-clock waits. Scenario durations are virtual seconds; the
wall cost of each test is its CPU work only.

The two tier-1 acceptance scenarios from ROADMAP item 3 are here:
byzantine-equivocator-under-load and partition-then-heal, each asserting
the safety oracle (no conflicting commits among honest nodes) and liveness
(rounds advance; post-heal for the partition).
"""

from __future__ import annotations

import asyncio
import time

import pytest

from narwhal_tpu.config import Parameters
from narwhal_tpu.simnet import (
    Crash,
    Equivocate,
    FaultPlan,
    LinkSpec,
    Partition,
    Reconfigure,
    SimDeadlockError,
    SimFabric,
    SimLoop,
    WorkerLoss,
    oracles,
    run_scenario,
)


# Calmer pacing than the defaults: fewer (bigger) rounds per virtual second
# keeps each scenario's CPU bill small without changing any semantics.
CALM = dict(max_header_delay=0.1, max_batch_delay=0.05)
CALM_PARAMS = Parameters(
    max_header_delay=0.1,
    max_batch_delay=0.05,
    header_delay_floor=0.05,
    batch_delay_floor=0.02,
)


# ---------------------------------------------------------------------------
# The virtual clock
# ---------------------------------------------------------------------------


def test_virtual_clock_sleeps_cost_no_wall_time():
    loop = SimLoop()
    try:
        t_wall = time.monotonic()
        t0 = loop.time()
        loop.run_until_complete(asyncio.sleep(3600.0))
        assert loop.time() - t0 >= 3600.0
        assert time.monotonic() - t_wall < 5.0  # an hour in milliseconds
    finally:
        loop.close()


def test_virtual_clock_orders_timers():
    loop = SimLoop()
    fired = []

    async def marker(delay, label):
        await asyncio.sleep(delay)
        fired.append((label, loop.time()))

    async def main():
        await asyncio.gather(marker(2.0, "b"), marker(1.0, "a"), marker(3.0, "c"))

    try:
        loop.run_until_complete(main())
    finally:
        loop.close()
    assert [l for l, _ in fired] == ["a", "b", "c"]
    assert [round(t, 6) for _, t in fired] == [1.0, 2.0, 3.0]


def test_virtual_clock_detects_deadlock():
    loop = SimLoop()

    async def stuck():
        await loop.create_future()  # nothing will ever resolve this

    try:
        with pytest.raises(SimDeadlockError):
            loop.run_until_complete(stuck())
    finally:
        # The failed main task is still pending; drop it quietly.
        for t in asyncio.all_tasks(loop):
            t.cancel()
        loop.close()


# ---------------------------------------------------------------------------
# The fabric as a transport (no committee): real rpc.py code, zero sockets
# ---------------------------------------------------------------------------


def test_fabric_carries_rpc_frames_and_partitions():
    from narwhal_tpu.messages import RequestBatchMsg, RequestedBatchMsg
    from narwhal_tpu.network import NetworkClient, RpcServer, transport
    from narwhal_tpu.network.rpc import RpcError

    loop = SimLoop()
    asyncio.set_event_loop(loop)
    fabric = SimFabric(seed=1, default_link=LinkSpec(latency=0.005))
    transport.install(fabric)
    fabric.register_node("a", ["hostb:1"])  # client side is unattributed

    async def main():
        server = RpcServer()

        async def echo(msg, peer):
            return RequestedBatchMsg(msg.digest, b"payload:" + msg.digest)

        bound = await server.start("hostb", 1)
        assert bound == 1
        server.route(RequestBatchMsg, echo)
        client = NetworkClient()
        t0 = loop.time()
        resp = await client.request("hostb:1", RequestBatchMsg(b"\x11" * 32))
        assert resp.serialized_batch == b"payload:" + b"\x11" * 32
        # Delivery paid the configured virtual latency, in virtual time.
        assert loop.time() - t0 >= 0.005
        # A downed server refuses fast (the crash model).
        fabric.set_node_down("a", True)
        with pytest.raises((RpcError, OSError)):
            await client.request("hostb:1", RequestBatchMsg(b"\x22" * 32), timeout=1.0)
        client.close()
        await server.stop()

    try:
        loop.run_until_complete(main())
        assert len(fabric.log) > 0
    finally:
        transport.uninstall()
        for t in asyncio.all_tasks(loop):
            t.cancel()
        loop.run_until_complete(asyncio.sleep(0))
        asyncio.set_event_loop(None)
        loop.close()


# ---------------------------------------------------------------------------
# Determinism: the replay acceptance criterion
# ---------------------------------------------------------------------------


def test_seeded_scenario_replays_bit_identically():
    """Same seed => identical commit sequences and identical event log;
    a different seed diverges. Full auth + jittery links + traffic + a
    partition event, so the claim covers handshakes, AEAD frames, retry
    timers AND the fault driver's connection-reset sweeps (whose iteration
    order once diverged between runs)."""

    def go(seed):
        return run_scenario(
            nodes=4,
            duration=1.5,
            load_rate=80,
            parameters=CALM_PARAMS,
            plan=FaultPlan(
                seed=seed,
                default_link=LinkSpec(latency=0.002, jitter=0.001),
                events=(Partition(at=0.4, heal=0.9, groups=((0, 1), (2, 3))),),
            ),
        )

    a = go(7)
    b = go(7)
    c = go(8)
    assert a.event_log_len == b.event_log_len
    assert a.event_log_digest == b.event_log_digest
    assert a.commits == b.commits
    assert a.rounds == b.rounds
    assert a.rounds[0] >= 2  # the run did real work
    assert c.event_log_digest != a.event_log_digest  # seeds matter


# ---------------------------------------------------------------------------
# Adversary scenarios (the tier-1 acceptance pair)
# ---------------------------------------------------------------------------


def test_partition_then_heal_safety_and_liveness():
    """A 2|2 split (neither side has quorum) stalls commits; after heal the
    committee recovers: no conflicting commits anywhere, rounds advance."""
    r = run_scenario(
        nodes=4,
        duration=4.0,
        plan=FaultPlan(
            seed=3,
            events=(Partition(at=0.5, heal=2.0, groups=((0, 1), (2, 3))),),
        ),
        **CALM,
    )
    oracles.assert_safety(r.commits)
    at_heal = r.round_marks["heal@2.0"]
    # 2|2 leaves no quorum: nobody commits meaningfully while split.
    assert max(at_heal) <= max(r.round_marks["partition@0.5"]) + 1
    # Liveness post-heal: every node advances again.
    oracles.assert_liveness(r.rounds, at_heal, min_rounds=2)


def test_byzantine_equivocator_under_load():
    """One authority signs conflicting headers every round and shows
    different ones to different halves of the committee, under client
    traffic. Honest nodes never commit conflicting sequences, and rounds
    keep advancing."""
    r = run_scenario(
        nodes=4,
        duration=2.5,
        load_rate=100,
        parameters=CALM_PARAMS,
        plan=FaultPlan(seed=4, events=(Equivocate(node=3),)),
    )
    assert r.equivocation[3]["twins_sent"] > 0  # the adversary really fired
    oracles.assert_safety(r.commits, honest=r.honest())
    oracles.assert_liveness(r.rounds, min_rounds=3, nodes=r.honest())
    # Execution agrees too (same committed payload order on honest nodes).
    assert r.identical_execution_prefix


def test_crash_restart_catches_up():
    r = run_scenario(
        nodes=4,
        duration=4.0,
        plan=FaultPlan(
            seed=5, events=(Crash(at=1.0, node=1, restart_at=2.0),)
        ),
        **CALM,
    )
    oracles.assert_safety(r.commits)
    # Survivors never stopped (3 of 4 is a quorum).
    oracles.assert_liveness(
        r.rounds, r.round_marks["crash@1.0"], min_rounds=2, nodes=[0, 2, 3]
    )
    # The restarted node rejoined and committed in its fresh segment.
    assert len(r.commits[1]) > 0


def test_worker_loss_mid_quorum_under_load():
    """Killing one of W=2 worker lanes mid-traffic must not stop commits:
    the surviving lane's batches keep certifying."""
    r = run_scenario(
        nodes=4,
        workers=2,
        duration=2.5,
        load_rate=80,
        parameters=CALM_PARAMS,
        plan=FaultPlan(seed=9, events=(WorkerLoss(at=1.0, node=1, worker_id=0),)),
    )
    oracles.assert_safety(r.commits)
    oracles.assert_liveness(
        r.rounds, r.round_marks["workerloss@1.0"], min_rounds=2
    )
    assert min(r.executed) > 0


def test_epoch_reconfiguration_under_sustained_traffic():
    """ROADMAP item 3's reconfiguration scenario, deterministic and fast
    under simnet: an in-band epoch change lands mid-traffic; the committee
    re-forms in epoch 1 and keeps committing and executing."""
    r = run_scenario(
        nodes=4,
        duration=3.5,
        load_rate=100,
        parameters=CALM_PARAMS,
        plan=FaultPlan(seed=6, events=(Reconfigure(at=1.5),)),
    )
    assert r.epochs == (0, 1)
    oracles.assert_safety(r.commits)
    # Commits kept happening after the epoch change on every node.
    for seq in r.commits:
        assert any(e == 1 for e, _, _ in seq), "no epoch-1 commits"
    assert min(r.executed) > 0


def test_link_jitter_and_loss_do_not_break_safety():
    """A degraded (slow, jittery, lossy) link between two nodes: the retry
    machinery reconnects through resets, and safety/liveness hold."""
    from narwhal_tpu.simnet import LinkFault

    r = run_scenario(
        nodes=4,
        duration=3.0,
        plan=FaultPlan(
            seed=12,
            events=(
                LinkFault(
                    at=0.0,
                    a=0,
                    b=2,
                    link=LinkSpec(latency=0.05, jitter=0.03, drop=0.02),
                ),
            ),
        ),
        **CALM,
    )
    oracles.assert_safety(r.commits)
    oracles.assert_liveness(r.rounds, min_rounds=3)


# ---------------------------------------------------------------------------
# Compact certificates under adversarial load (ISSUE 11: the committee-wide
# default wire form must survive the same adversaries full certificates do,
# on a cpu-backend committee whose proofs verify through the batched host
# MSM inside the simulation)
# ---------------------------------------------------------------------------

COMPACT_PARAMS = Parameters(
    max_header_delay=0.1,
    max_batch_delay=0.05,
    header_delay_floor=0.05,
    batch_delay_floor=0.02,
    cert_format="compact",  # explicit: this coverage must survive a
    verify_rule="strict",   # default flip either way
)


def test_compact_committee_survives_equivocator_under_load():
    """Byzantine equivocator against a compact-certificate cpu committee:
    twins really fire, honest safety/liveness hold, execution prefixes
    agree — and the committed DAG is genuinely half-aggregated (every
    stored non-genesis certificate is compact)."""
    r = run_scenario(
        nodes=4,
        duration=2.5,
        load_rate=100,
        parameters=COMPACT_PARAMS,
        plan=FaultPlan(seed=21, events=(Equivocate(node=3),)),
    )
    assert r.equivocation[3]["twins_sent"] > 0
    oracles.assert_safety(r.commits, honest=r.honest())
    oracles.assert_liveness(r.rounds, min_rounds=3, nodes=r.honest())
    assert r.identical_execution_prefix
    for forms in r.cert_forms:
        assert forms["compact"] > 0 and forms["full"] == 0, r.cert_forms


def test_compact_committee_partition_then_heal():
    """2|2 split on a compact committee: commits stall (no quorum), heal
    restores liveness, no conflicting commits — and the recovered rounds'
    certificates are all compact."""
    r = run_scenario(
        nodes=4,
        duration=4.0,
        parameters=COMPACT_PARAMS,
        plan=FaultPlan(
            seed=22,
            events=(Partition(at=0.5, heal=2.0, groups=((0, 1), (2, 3))),),
        ),
    )
    oracles.assert_safety(r.commits)
    at_heal = r.round_marks["heal@2.0"]
    assert max(at_heal) <= max(r.round_marks["partition@0.5"]) + 1
    oracles.assert_liveness(r.rounds, at_heal, min_rounds=2)
    for forms in r.cert_forms:
        assert forms["compact"] > 0 and forms["full"] == 0, r.cert_forms
