"""The shared kernel registry: one compile per (kernel, mesh shape).

These are the tier-1-fast mesh tests of the multi-chip device plane
(ISSUE 10): they run on conftest's virtual CPU devices and deliberately
share their mesh + bucket shapes with tests/test_multichip.py's dryrun
legs, so the suite pays each sharded kernel compile once no matter which
file runs first.

The recompile guard uses `jax_log_compiles`: with it on, every XLA
compile emits a 'Compiling <name> ...' log record, so 'one compile per
(kernel, mesh shape) per process' is asserted against jax's own
accounting rather than wall-clock heuristics.
"""

from __future__ import annotations

import logging

import numpy as np
import pytest

import jax

from narwhal_tpu.tpu import kernel_registry


def _data_mesh(n):
    from narwhal_tpu.tpu.verifier import data_mesh

    cpus = jax.devices("cpu")
    if len(cpus) < n:
        pytest.skip(f"need {n} cpu devices")
    return data_mesh(n, devices=cpus[:n])


def _auth_mesh(n):
    from jax.sharding import Mesh

    cpus = jax.devices("cpu")
    if len(cpus) < n:
        pytest.skip(f"need {n} cpu devices")
    return Mesh(np.array(cpus[:n]), ("auth",))


class _CompileLog(logging.Handler):
    """Captures jax's 'Compiling <fn> ...' records while installed."""

    def __init__(self):
        super().__init__(level=logging.DEBUG)
        self.compiles: list[str] = []

    def emit(self, record):
        msg = record.getMessage()
        if msg.startswith("Compiling "):
            self.compiles.append(msg)

    def count(self, name: str) -> int:
        return sum(1 for m in self.compiles if m.startswith(f"Compiling {name}"))


@pytest.fixture
def compile_log():
    jax.config.update("jax_log_compiles", True)
    handler = _CompileLog()
    jax_logger = logging.getLogger("jax")
    old_level = jax_logger.level
    jax_logger.addHandler(handler)
    jax_logger.setLevel(logging.DEBUG)
    try:
        yield handler
    finally:
        jax_logger.removeHandler(handler)
        jax_logger.setLevel(old_level)
        jax.config.update("jax_log_compiles", False)


def test_module_kernels_are_registered():
    """Every jit entry point in tpu/ lands in the registry catalog (the
    runtime half of the no-untracked-jit lint rule)."""
    import narwhal_tpu.tpu.dag_kernels  # noqa: F401
    import narwhal_tpu.tpu.ed25519  # noqa: F401

    names = set(kernel_registry.kernel_names())
    assert {
        "reach_mask",
        "roll_window",
        "place_batch",
        "leader_support",
        "chain_commit",
        "verify_batch_kernel",
        "msm_accumulate_kernel",
        "verify_decompress_kernel",
        "verify_straus_kernel",
        "verify_verdict_kernel",
        "msm_window_kernel",
    } <= names


def test_sharded_wrappers_are_process_wide():
    """Two fetches of the same (kernel, mesh, specs) return the SAME
    wrapper object — the structural guarantee that a second verifier or
    engine over the mesh can never pay a second compile."""
    from jax.sharding import PartitionSpec as P

    from narwhal_tpu.tpu.dag_kernels import chain_commit

    mesh = _auth_mesh(2)
    specs = dict(
        in_specs=(
            P(None, None, "auth"),
            P(None, "auth"),
            None,
            P("auth"),
            None,
            None,
            P(None, None),
        ),
        out_specs=P(None, None, "auth"),
    )
    k1 = kernel_registry.sharded(chain_commit, mesh, **specs)
    k2 = kernel_registry.sharded(chain_commit, mesh, **specs)
    assert k1 is k2
    # A different mesh shape is a different program.
    k3 = kernel_registry.sharded(chain_commit, _auth_mesh(4), **specs)
    assert k3 is not k1


def test_verifier_modes_share_staged_kernels():
    """The dryrun's historical double-compile: an item-mode and an
    msm-mode verifier over the SAME mesh must dispatch through identical
    stage wrappers (the msm fallback path reuses the item stages)."""
    from narwhal_tpu.tpu import ed25519 as kernel
    from narwhal_tpu.tpu.verifier import _sharded_kernels

    mesh = _data_mesh(4)
    before = kernel_registry.sharded_entries()
    _sharded_kernels(kernel, mesh, "data")
    after_first = kernel_registry.sharded_entries()
    _sharded_kernels(kernel, mesh, "data")
    assert kernel_registry.sharded_entries() == after_first
    assert after_first > before  # the first build did register stages


def test_one_compile_per_kernel_mesh_shape(compile_log):
    """The recompile guard: dispatching the registry's chain_commit
    wrapper for one (mesh, operand-shape) tuple from TWO consumers
    compiles exactly once per mesh shape — pinned via jax_log_compiles."""
    from jax.sharding import PartitionSpec as P

    from narwhal_tpu.tpu.dag_kernels import chain_commit

    W, N = 8, 4
    args = (
        np.zeros((W, N, N), np.uint8),
        np.zeros((W, N), np.uint8),
        np.int32(2),
        np.zeros((N,), np.int32),
        np.int32(-1),
        np.zeros((1,), np.int32),
        np.zeros((1, N), np.uint8),
    )
    specs = dict(
        in_specs=(
            P(None, None, "auth"),
            P(None, "auth"),
            None,
            P("auth"),
            None,
            None,
            P(None, None),
        ),
        out_specs=P(None, None, "auth"),
    )
    mesh = _auth_mesh(2)
    k1 = kernel_registry.sharded(chain_commit, mesh, **specs)
    jax.block_until_ready(k1(*args))
    first = compile_log.count("chain_commit")
    assert first >= 1  # this (mesh, shape) had not been dispatched before

    # Second consumer, same mesh + shapes: zero new compiles.
    k2 = kernel_registry.sharded(chain_commit, mesh, **specs)
    jax.block_until_ready(k2(*args))
    jax.block_until_ready(k1(*args))
    assert compile_log.count("chain_commit") == first

    # A new mesh shape compiles once more; repeating it does not.
    k4 = kernel_registry.sharded(chain_commit, _auth_mesh(4), **specs)
    jax.block_until_ready(k4(*args))
    second = compile_log.count("chain_commit")
    assert second == first + 1
    jax.block_until_ready(k4(*args))
    assert compile_log.count("chain_commit") == second


def test_compile_walls_recorded():
    """First dispatches self-report their walls per (kernel, mesh shape) —
    the accounting the dryrun/bench artifacts embed."""
    from jax.sharding import PartitionSpec as P

    from narwhal_tpu.tpu.dag_kernels import chain_commit

    mesh = _auth_mesh(2)
    k = kernel_registry.sharded(
        chain_commit,
        mesh,
        in_specs=(
            P(None, None, "auth"),
            P(None, "auth"),
            None,
            P("auth"),
            None,
            None,
            P(None, None),
        ),
        out_specs=P(None, None, "auth"),
    )
    W, N = 8, 4
    jax.block_until_ready(
        k(
            np.zeros((W, N, N), np.uint8),
            np.zeros((W, N), np.uint8),
            np.int32(2),
            np.zeros((N,), np.int32),
            np.int32(-1),
            np.zeros((1,), np.int32),
            np.zeros((1, N), np.uint8),
        )
    )
    walls = kernel_registry.compile_walls()
    rows = [r for r in walls if r["kernel"] == "chain_commit" and r["mesh"] == "2:auth"]
    assert rows and all(r["wall_s"] >= 0 for r in rows)
    agg = kernel_registry.compile_walls_by_shape()
    assert "chain_commit@2:auth" in agg


def test_verify_shard_divisibility_still_fails_fast():
    """Mesh sizing errors stay construction-time errors through the
    registry path (the advisor-r4 rule: stop the node at startup)."""
    from narwhal_tpu.config import ConfigError
    from narwhal_tpu.tpu.verifier import TpuVerifier

    mesh = _data_mesh(3)
    with pytest.raises(ConfigError):
        TpuVerifier(max_bucket=32, mode="item", mesh=mesh)  # 16 % 3 != 0


def test_sharded_verifier_verdicts_match_host():
    """Tier-1 mesh verdict equivalence: the STAGED sharded pipeline (both
    accept-set modes) against the host library on a batch mixing valid
    signatures, a forgery, a malformed signature and a wrong-length key.
    Shares mesh (4-device 'data') and bucket (32) with the dryrun leg in
    test_multichip.py, so the compile is paid once per suite process.
    Exact bit-equivalence of staged-vs-monolithic kernels is pinned in the
    slow lane (test_tpu_ed25519.py)."""
    from narwhal_tpu import crypto
    from narwhal_tpu.crypto import KeyPair
    from narwhal_tpu.tpu.verifier import TpuVerifier

    mesh = _data_mesh(4)
    kp = KeyPair.generate()
    items = [(kp.public, b"m%d" % i, kp.sign(b"m%d" % i)) for i in range(28)]
    items.append((kp.public, b"forged", kp.sign(b"not-forged")))  # wrong msg
    items.append((kp.public, b"mangled", b"\x00" * 64))  # junk signature
    items.append((kp.public[:16], b"short", kp.sign(b"short")))  # bad key len
    items.append((kp.public, b"ok-tail", kp.sign(b"ok-tail")))
    expected = crypto._host_batch_verify(items)
    assert expected[:28] == [True] * 28 and expected[28:31] == [False] * 3

    for mode in ("item", "msm"):
        v = TpuVerifier(max_bucket=32, msm_min_bucket=16, mode=mode, mesh=mesh)
        got = v(items)
        assert got == expected, f"sharded {mode} verdicts diverged from host"
        assert v(items) == expected  # compiled-path dispatch is stable


def test_auth_axis_committee_padding():
    """Committee sizes that don't divide the 'auth' axis are padded with
    always-absent authority slots: zero stake, never present, invisible
    to reachability — and an exactly-divisible committee pads nothing.
    (Commit-sequence equivalence of the padded engine is pinned in
    tests/test_dag_kernels.py::test_equivalence_mesh_padded_committee.)"""
    from narwhal_tpu.fixtures import CommitteeFixture
    from narwhal_tpu.tpu.dag_kernels import TpuBullshark

    mesh = _auth_mesh(2)
    f7 = CommitteeFixture(size=7)
    eng = TpuBullshark(f7.committee, None, 50, mesh=mesh, prewarm=False)
    assert eng.win.N == 8  # 7 -> next multiple of auth=2
    assert eng.win.stakes[7] == 0  # padded slot carries no stake
    assert not eng.win.present[:, 7].any()  # ... and never a certificate

    f4 = CommitteeFixture(size=4)
    eng4 = TpuBullshark(f4.committee, None, 50, mesh=mesh, prewarm=False)
    assert eng4.win.N == 4  # divisible: no padding
