"""PR-9 regression fixture: os.urandom handshake nonces, verbatim shape.

The auth handshake drew its anti-replay nonce straight from the OS, so
the handshake transcript — and everything keyed off it — differed
between two runs of the same seeded scenario. The fix routed the draw
through the `auth.set_entropy` seam; this fixture pins that `raw-entropy`
re-finds the original shape.
"""

import os


def client_handshake(writer, static_key: bytes) -> bytes:
    nonce = os.urandom(32)  # BUG (PR-9): ambient entropy in the handshake
    writer.write(static_key + nonce)
    return nonce
