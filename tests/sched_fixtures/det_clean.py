"""Clean twin of det_trip.py: the deterministic version of every shape.

Must produce ZERO findings — pinned by test. Each method fixes its
det_trip counterpart the way protocol code is expected to.
"""

import random


class Broadcaster:
    def __init__(self, rng: random.Random | None = None, seed: int = 0):
        self.peers: set = set()
        self.rng = rng if rng is not None else random.Random(seed)

    def fresh_id(self, counter: int, node: str) -> str:
        return f"{node}:{counter}"  # stable protocol identity

    def jitter(self) -> float:
        return self.rng.uniform(0.0, 1.0)  # injected seeded stream

    def private_rng(self, seed: int):
        return random.Random(seed)

    def dedup_key(self, msg) -> bytes:
        return msg.digest  # content-derived, replay-stable

    def flood(self, msg) -> None:
        for peer in sorted(self.peers, key=lambda p: p.name):
            peer.send(msg)
