"""Clean twin of races_trip.py: the same two tasks, disciplined.

All Board mutation flows through Board's own methods (one container, one
encapsulation boundary) and Counter.bump does its read-modify-write
atomically AFTER the yield point — zero findings, pinned by test.
"""

import asyncio


class Board:
    def __init__(self):
        self.slots: dict = {}
        self.total = 0

    def post(self, key, value) -> None:
        self.slots[key] = value
        self.total += 1

    def occupancy(self) -> int:
        return len(self.slots)


class Counter:
    def __init__(self):
        self.count = 0

    async def bump(self) -> None:
        await asyncio.sleep(0)
        self.count += 1  # read and write on one side of the yield


class Writer:
    def __init__(self, board, counter):
        self.board = board
        self.counter = counter

    async def run(self) -> None:
        self.board.post("w", 1)
        await self.counter.bump()


class Reader:
    def __init__(self, board, counter):
        self.board = board
        self.counter = counter

    async def run(self) -> None:
        self.board.post("r", self.board.occupancy())
        await self.counter.bump()


def main():
    board = Board()
    counter = Counter()
    writer = Writer(board, counter)
    reader = Reader(board, counter)
    asyncio.create_task(writer.run())
    asyncio.create_task(reader.run())
