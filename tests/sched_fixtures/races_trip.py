"""Tripping fixture for the race family, driven from `main` as the
extraction root (package='' so the program is just this file).

Two independent shapes:

* `Board` — unencapsulated sharing: Writer's task and Reader's task both
  poke `board.slots` / `board.total` directly from their own class
  bodies. Two containers, two writer tasks -> `multi-task-mutation`.
* `Counter` — encapsulated but yield-unsafe: both tasks call
  `Counter.bump`, whose read of `self.count` and write-back straddle an
  await -> `await-interleaved-rmw` (a lost update, the classic shape).
"""

import asyncio


class Board:
    def __init__(self):
        self.slots: dict = {}
        self.total = 0


class Counter:
    def __init__(self):
        self.count = 0

    async def bump(self) -> None:
        current = self.count
        await asyncio.sleep(0)
        self.count = current + 1


class Writer:
    def __init__(self, board, counter):
        self.board = board
        self.counter = counter

    async def run(self) -> None:
        self.board.slots["w"] = 1
        self.board.total += 1
        await self.counter.bump()


class Reader:
    def __init__(self, board, counter):
        self.board = board
        self.counter = counter

    async def run(self) -> None:
        seen = len(self.board.slots)
        self.board.slots["r"] = seen
        await self.counter.bump()


def main():
    board = Board()
    counter = Counter()
    writer = Writer(board, counter)
    reader = Reader(board, counter)
    asyncio.create_task(writer.run())
    asyncio.create_task(reader.run())
