"""Tripping fixture for the determinism family: one hit per shape.

Each statement below is a distinct detector shape with a pinned count in
tests/test_static_analysis.py — keep them one-per-line and update the
pins when adding shapes.
"""

import random
import uuid


class Broadcaster:
    def __init__(self, rng=None):
        self.peers: set = set()
        self.rng = rng or random  # unseeded-random: module object as RNG

    def fresh_id(self) -> str:
        return uuid.uuid4().hex  # raw-entropy

    def jitter(self) -> float:
        return random.uniform(0.0, 1.0)  # unseeded-random: global draw

    def private_rng(self):
        return random.Random()  # unseeded-random: no seed

    def dedup_key(self, msg) -> int:
        return id(msg)  # id-keyed-ordering

    def flood(self, msg) -> None:
        for peer in self.peers:  # unordered-iteration: effectful set loop
            peer.send(msg)
