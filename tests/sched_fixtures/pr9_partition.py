"""PR-9 regression fixture: the set_partition divergence, verbatim shape.

The simnet fabric kept live connections in a `set` and reset them on a
partition change by iterating it directly. Connection resets are
observable wire effects, so two runs of the same seeded scenario reset
in different (hash) orders and their logs diverged — found by hand A/B
log diffing, now pinned as what `unordered-iteration` must re-find.
"""


class Fabric:
    def __init__(self):
        self._conns: set = set()
        self._partition: tuple = ()

    def register(self, conn) -> None:
        self._conns.add(conn)

    def set_partition(self, groups) -> None:
        self._partition = tuple(tuple(sorted(g)) for g in groups)
        for conn in self._conns:  # BUG (PR-9): hash-order resets
            conn.reset(self._partition)
