"""The pipelined commit-to-execution data plane.

Covers the four layers of the coalesced-fetch + speculative-prefetch path:
wire (RequestBatchesMsg answers byte-identical to N sequential RequestBatch
calls), the subscriber's one-RPC-per-(worker, certificate) staging, the
prefetcher's warm-cache / budget-eviction / gc_depth semantics, and the
escalating diagnostics for permanently-failing fetches.
"""

import asyncio
import logging

import pytest

from narwhal_tpu.channels import Channel
from narwhal_tpu.executor.metrics import ExecutorMetrics
from narwhal_tpu.executor.prefetcher import Prefetcher
from narwhal_tpu.executor.subscriber import Subscriber
from narwhal_tpu.fixtures import CommitteeFixture
from narwhal_tpu.messages import (
    RequestBatchesMsg,
    RequestBatchMsg,
    RequestedBatchesMsg,
)
from narwhal_tpu.metrics import Registry
from narwhal_tpu.network import NetworkClient, RpcServer
from narwhal_tpu.stores import NodeStorage
from narwhal_tpu.types import Batch, ConsensusOutput
from narwhal_tpu.worker import Worker


def _rewire_worker(f, port: int) -> None:
    from narwhal_tpu.config import WorkerInfo

    pk = f.authorities[0].public
    info = f.worker_cache.workers[pk][0]
    f.worker_cache.workers[pk][0] = WorkerInfo(
        name=info.name,
        transactions=info.transactions,
        worker_address=f"127.0.0.1:{port}",
    )


def _counting_server(*batches: Batch):
    """(server, calls) where the server answers RequestBatchesMsg from
    `batches` with authoritative found flags and counts fetch RPCs."""
    by_digest = {b.digest: b.to_bytes() for b in batches}
    calls = {"rpcs": 0}
    srv = RpcServer()

    async def on_request(msg: RequestBatchesMsg, peer):
        calls["rpcs"] += 1
        return RequestedBatchesMsg(
            tuple((d, d in by_digest, by_digest.get(d, b"")) for d in msg.digests)
        )

    srv.route(RequestBatchesMsg, on_request)
    return srv, calls


def _subscriber(f, temp_store, metrics=None, prefetcher=None, **kw) -> Subscriber:
    return Subscriber(
        f.authorities[0].public,
        f.worker_cache,
        NetworkClient(),
        temp_store,
        rx_consensus=Channel(100),
        tx_executor=Channel(100),
        metrics=metrics,
        prefetcher=prefetcher,
        **kw,
    )


def _output(f, batches, round=1, index=0) -> ConsensusOutput:
    cert = f.certificate(
        f.header(author=0, round=round, payload={b.digest: 0 for b in batches})
    )
    return ConsensusOutput(certificate=cert, consensus_index=index)


# ---------------------------------------------------------------------------
# Wire equivalence
# ---------------------------------------------------------------------------


def test_coalesced_fetch_equivalent_to_sequential(run):
    """One RequestBatchesMsg against a REAL worker returns entries
    byte-identical to N sequential RequestBatchMsg calls, found and
    not-found digests mixed, in request order."""

    async def scenario():
        f = CommitteeFixture(size=4)
        have = [Batch((b"tx-%d" % i, b"tx2-%d" % i)) for i in range(3)]
        lack = [Batch((b"missing-%d" % i,)) for i in range(2)]
        store = NodeStorage(None).batch_store
        for b in have:
            store.write(b.digest, b.to_bytes())
        w = Worker(
            f.authorities[0].public, 0, f.committee, f.worker_cache,
            f.parameters, store,
        )
        await w.spawn()
        try:
            host_port = w.worker_address
            net = NetworkClient()
            # Interleave found and not-found digests.
            digests = []
            for h, m in zip(have, lack + [None, None]):
                digests.append(h.digest)
                if m is not None:
                    digests.append(m.digest)
            sequential = [
                await net.request(host_port, RequestBatchMsg(d)) for d in digests
            ]
            coalesced = await net.request(
                host_port, RequestBatchesMsg(tuple(digests))
            )
            assert len(coalesced.batches) == len(digests)
            for (cd, cfound, craw), seq, d in zip(
                coalesced.batches, sequential, digests
            ):
                assert cd == seq.digest == d
                assert cfound == seq.found
                assert craw == seq.serialized_batch  # byte-identical
            net.close()
        finally:
            await w.shutdown()

    run(scenario())


# ---------------------------------------------------------------------------
# Subscriber staging: RPC coalescing
# ---------------------------------------------------------------------------


def test_staging_issues_one_rpc_for_sixteen_batches(run):
    """The ISSUE acceptance bound: at 16 batches/certificate on one worker,
    the coalesced plane issues >=8x fewer fetch RPCs than the per-batch
    plane would (here: exactly 1 vs 16)."""

    async def scenario():
        f = CommitteeFixture(size=4)
        batches = [Batch((b"tx-%d" % i,)) for i in range(16)]
        srv, calls = _counting_server(*batches)
        port = await srv.start("127.0.0.1", 0)
        _rewire_worker(f, port)
        registry = Registry()
        metrics = ExecutorMetrics(registry)
        storage = NodeStorage(None)
        sub = _subscriber(f, storage.temp_batch_store, metrics=metrics)
        try:
            output = _output(f, batches)
            staged_output, staged, _t = await asyncio.wait_for(
                sub._stage(output, 0.0), 10.0
            )
            assert staged_output is output
            assert set(staged) == {b.digest for b in batches}
            assert calls["rpcs"] == 1
            assert len(batches) / calls["rpcs"] >= 8  # the acceptance bound
            # The RPCs-per-certificate histogram saw one observation of 1.
            h = registry.get("executor_fetch_rpcs_per_certificate")
            child = h._default()
            assert child.count == 1 and child.sum == 1.0
            assert registry.value("executor_bytes_fetched") == sum(
                len(b.to_bytes()) for b in batches
            )
            sub.network.close()
        finally:
            await srv.stop()

    run(scenario())


def test_staging_groups_by_worker(run):
    """Batches spread over two workers cost one RPC per worker, issued
    concurrently, and partial progress is preserved across retries."""

    async def scenario():
        f = CommitteeFixture(size=4, workers=2)
        b0 = [Batch((b"w0-%d" % i,)) for i in range(4)]
        b1 = [Batch((b"w1-%d" % i,)) for i in range(4)]
        srv0, calls0 = _counting_server(*b0)
        srv1, calls1 = _counting_server(*b1)
        from narwhal_tpu.config import WorkerInfo

        pk = f.authorities[0].public
        for wid, srv in ((0, srv0), (1, srv1)):
            port = await srv.start("127.0.0.1", 0)
            info = f.worker_cache.workers[pk][wid]
            f.worker_cache.workers[pk][wid] = WorkerInfo(
                name=info.name,
                transactions=info.transactions,
                worker_address=f"127.0.0.1:{port}",
            )
        storage = NodeStorage(None)
        sub = _subscriber(f, storage.temp_batch_store)
        try:
            payload = {b.digest: 0 for b in b0} | {b.digest: 1 for b in b1}
            cert = f.certificate(f.header(author=0, round=1, payload=payload))
            output = ConsensusOutput(certificate=cert, consensus_index=0)
            _, staged, _t = await asyncio.wait_for(sub._stage(output, 0.0), 10.0)
            assert set(staged) == set(payload)
            assert calls0["rpcs"] == 1 and calls1["rpcs"] == 1
            sub.network.close()
        finally:
            await srv0.stop()
            await srv1.stop()

    run(scenario())


def test_unknown_worker_id_escalates_to_warning(run, caplog):
    """A payload naming a worker id absent from the worker cache used to
    retry forever in silence (KeyError swallowed at debug); after ~5
    attempts it must surface as a rate-limited warning with the attempt
    count."""

    async def scenario():
        f = CommitteeFixture(size=4)
        batch = Batch((b"tx",))
        storage = NodeStorage(None)
        sub = _subscriber(
            f, storage.temp_batch_store, initial_backoff=0.001, max_backoff=0.002
        )
        cert = f.certificate(
            f.header(author=0, round=1, payload={batch.digest: 7})  # no worker 7
        )
        output = ConsensusOutput(certificate=cert, consensus_index=0)
        with caplog.at_level(logging.WARNING, logger="narwhal.executor"):
            task = asyncio.ensure_future(sub._stage(output, 0.0))
            for _ in range(400):
                await asyncio.sleep(0.005)
                if any(
                    "still failing after" in r.message for r in caplog.records
                ):
                    break
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
        warnings = [r for r in caplog.records if "still failing after" in r.message]
        assert warnings, "unknown worker_id never escalated past debug"
        assert "unknown worker id 7" in warnings[0].getMessage()
        sub.network.close()

    run(scenario())


# ---------------------------------------------------------------------------
# Prefetcher
# ---------------------------------------------------------------------------


def _prefetcher(f, temp_store, metrics=None, **kw) -> Prefetcher:
    return Prefetcher(
        f.authorities[0].public,
        f.worker_cache,
        NetworkClient(),
        temp_store,
        rx_accepted=Channel(100),
        retry_delay=0.01,
        metrics=metrics,
        **kw,
    )


def test_warm_commit_is_a_local_hit_with_zero_rpcs(run):
    """An accepted certificate's payload prefetched before commit makes the
    commit-time staging pass entirely local: the prefetch hit-rate metric is
    >0 and staging issues zero fetch RPCs."""

    async def scenario():
        f = CommitteeFixture(size=4)
        batches = [Batch((b"tx-%d" % i,)) for i in range(4)]
        srv, calls = _counting_server(*batches)
        port = await srv.start("127.0.0.1", 0)
        _rewire_worker(f, port)
        registry = Registry()
        metrics = ExecutorMetrics(registry)
        storage = NodeStorage(None)
        pf = _prefetcher(f, storage.temp_batch_store, metrics=metrics)
        sub = _subscriber(f, storage.temp_batch_store, metrics=metrics, prefetcher=pf)
        try:
            output = _output(f, batches)
            # Acceptance-time: the certificate enters the DAG; the
            # prefetcher warms the store rounds before commit.
            await asyncio.wait_for(
                pf._prefetch_burst([output.certificate]), 10.0
            )
            assert calls["rpcs"] == 1
            assert registry.value("executor_prefetched_batches") == len(batches)
            assert pf.resident_bytes == sum(len(b.to_bytes()) for b in batches)
            # Commit-time: staging never touches the network.
            _, staged, _t = await asyncio.wait_for(sub._stage(output, 0.0), 10.0)
            assert set(staged) == {b.digest for b in batches}
            assert calls["rpcs"] == 1  # no NEW rpcs at commit
            assert registry.value("executor_prefetch_hits") > 0
            assert registry.value("executor_prefetch_misses") == 0
            # claim(): the commit took ownership of every prefetched entry.
            assert pf.resident_bytes == 0
            pf.network.close()
            sub.network.close()
        finally:
            await srv.stop()

    run(scenario())


def test_budget_eviction_falls_back_to_fetch(run):
    """Over-budget speculation evicts the OLDEST unclaimed payload; a later
    commit of the evicted certificate misses locally and transparently falls
    back to the coalesced fetch — eviction can cost a round trip, never
    correctness."""

    async def scenario():
        f = CommitteeFixture(size=4)
        b1 = Batch((b"first-" + b"x" * 64,))
        b2 = Batch((b"second-" + b"y" * 64,))
        srv, calls = _counting_server(b1, b2)
        port = await srv.start("127.0.0.1", 0)
        _rewire_worker(f, port)
        storage = NodeStorage(None)
        # Budget fits exactly one of the two batches.
        budget = max(len(b1.to_bytes()), len(b2.to_bytes())) + 8
        pf = _prefetcher(f, storage.temp_batch_store, budget_bytes=budget)
        sub = _subscriber(f, storage.temp_batch_store, prefetcher=pf)
        try:
            out1 = _output(f, [b1], round=1, index=0)
            out2 = _output(f, [b2], round=2, index=1)
            await asyncio.wait_for(pf._prefetch_burst([out1.certificate]), 10.0)
            await asyncio.wait_for(pf._prefetch_burst([out2.certificate]), 10.0)
            # b1 was evicted to admit b2.
            assert storage.temp_batch_store.read(b1.digest) is None
            assert storage.temp_batch_store.read(b2.digest) is not None
            assert pf.resident_bytes <= budget
            rpcs_before = calls["rpcs"]
            # Committing the evicted certificate still succeeds — via fetch.
            _, staged1, _t = await asyncio.wait_for(sub._stage(out1, 0.0), 10.0)
            assert staged1[b1.digest] == b1
            assert calls["rpcs"] == rpcs_before + 1
            # The warm certificate commits with zero new RPCs.
            _, staged2, _t = await asyncio.wait_for(sub._stage(out2, 0.0), 10.0)
            assert staged2[b2.digest] == b2
            assert calls["rpcs"] == rpcs_before + 1
            pf.network.close()
            sub.network.close()
        finally:
            await srv.stop()

    run(scenario())


def test_claimed_payload_is_never_evicted(run):
    """Once a commit claims its digests (committed-but-unexecuted), budget
    pressure from later speculation must not delete them from the store."""

    async def scenario():
        f = CommitteeFixture(size=4)
        b1 = Batch((b"committed-" + b"x" * 64,))
        b2 = Batch((b"speculative-" + b"y" * 64,))
        srv, calls = _counting_server(b1, b2)
        port = await srv.start("127.0.0.1", 0)
        _rewire_worker(f, port)
        storage = NodeStorage(None)
        budget = max(len(b1.to_bytes()), len(b2.to_bytes())) + 8
        pf = _prefetcher(f, storage.temp_batch_store, budget_bytes=budget)
        sub = _subscriber(f, storage.temp_batch_store, prefetcher=pf)
        try:
            out1 = _output(f, [b1], round=1, index=0)
            await asyncio.wait_for(pf._prefetch_burst([out1.certificate]), 10.0)
            # Commit claims b1: ownership moves to the execution path.
            await asyncio.wait_for(sub._stage(out1, 0.0), 10.0)
            # Later speculation would have evicted b1 under budget pressure;
            # claimed entries are no longer eviction candidates.
            out2 = _output(f, [b2], round=2, index=1)
            await asyncio.wait_for(pf._prefetch_burst([out2.certificate]), 10.0)
            assert storage.temp_batch_store.read(b1.digest) is not None
            assert storage.temp_batch_store.read(b2.digest) is not None
            pf.network.close()
            sub.network.close()
        finally:
            await srv.stop()

    run(scenario())


def test_never_committed_prefetch_gcd_past_gc_depth(run):
    """Speculative payload of a certificate that never commits is deleted
    once the accepted round-front moves gc_depth past its round — exactly
    the DAG's garbage horizon, so lost branches can't leak store bytes."""

    async def scenario():
        from narwhal_tpu.fixtures import mock_certificate
        from narwhal_tpu.types import Certificate

        f = CommitteeFixture(size=4)
        batch = Batch((b"never-commits",))
        srv, calls = _counting_server(batch)
        port = await srv.start("127.0.0.1", 0)
        _rewire_worker(f, port)
        storage = NodeStorage(None)
        pf = _prefetcher(f, storage.temp_batch_store, gc_depth=5)
        try:
            loser = _output(f, [batch], round=1)
            await asyncio.wait_for(pf._prefetch_burst([loser.certificate]), 10.0)
            assert storage.temp_batch_store.read(batch.digest) is not None
            # The round front advances without that certificate committing.
            genesis = {c.digest for c in Certificate.genesis(f.committee)}
            front = [
                mock_certificate(
                    f.committee, f.authorities[1].public, r, genesis
                )
                for r in (3, 7)
            ]
            await asyncio.wait_for(pf._prefetch_burst(front), 10.0)
            assert storage.temp_batch_store.read(batch.digest) is None
            assert pf.resident_bytes == 0
            pf.network.close()
        finally:
            await srv.stop()

    run(scenario())


def test_prefetcher_actor_end_to_end_via_tap_channel(run):
    """The spawned actor drains the accepted-certificate tap and warms the
    store in the background (the node.py wiring, minus the primary)."""

    async def scenario():
        f = CommitteeFixture(size=4)
        batches = [Batch((b"bg-%d" % i,)) for i in range(3)]
        srv, calls = _counting_server(*batches)
        port = await srv.start("127.0.0.1", 0)
        _rewire_worker(f, port)
        storage = NodeStorage(None)
        pf = _prefetcher(f, storage.temp_batch_store)
        task = pf.spawn()
        try:
            output = _output(f, batches)
            await pf.rx_accepted.send(output.certificate)
            for _ in range(200):
                if all(
                    storage.temp_batch_store.read(b.digest) is not None
                    for b in batches
                ):
                    break
                await asyncio.sleep(0.01)
            assert all(
                storage.temp_batch_store.read(b.digest) is not None
                for b in batches
            )
            assert calls["rpcs"] == 1
        finally:
            task.cancel()
            pf.network.close()
            await srv.stop()

    run(scenario())
