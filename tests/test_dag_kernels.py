"""TPU DAG kernel equivalence: the vectorized adjacency-tensor commit walk
must reproduce the host engine's sequence bit-for-bit on arbitrary DAGs.
Runs on the virtual CPU backend (conftest); bench.py exercises the same
kernels on the real chip."""

import random

import numpy as np
import pytest

from narwhal_tpu.consensus import Bullshark, ConsensusState
from narwhal_tpu.fixtures import CommitteeFixture, make_certificates, make_optimal_certificates
from narwhal_tpu.stores import NodeStorage
from narwhal_tpu.tpu.dag_kernels import DagWindow, TpuBullshark, leader_support, reach_mask
from narwhal_tpu.types import Certificate

from tests.test_consensus import fixed_leader

GC = 50


def _run_both(
    size, rounds, failure, seed, gc=GC, leader_fn=fixed_leader, window=None,
    host_cls=Bullshark, dev_cls=TpuBullshark, dev_kwargs=None,
):
    f = CommitteeFixture(size=size)
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    certs, _ = make_certificates(
        f.committee, 1, rounds, genesis,
        failure_probability=failure, rng=random.Random(seed),
    )
    host_state = ConsensusState(Certificate.genesis(f.committee))
    tpu_state = ConsensusState(Certificate.genesis(f.committee))
    host = host_cls(f.committee, NodeStorage(None).consensus_store, gc, leader_fn=leader_fn)
    dev = dev_cls(f.committee, NodeStorage(None).consensus_store, gc,
                  leader_fn=leader_fn, window=window, **(dev_kwargs or {}))
    host_seq, dev_seq = [], []
    hi = di = 0
    for c in certs:
        hs = host.process_certificate(host_state, hi, c)
        ds = dev.process_certificate(tpu_state, di, c)
        hi += len(hs)
        di += len(ds)
        host_seq.extend(hs)
        dev_seq.extend(ds)
        assert [o.certificate.digest for o in hs] == [o.certificate.digest for o in ds], (
            f"diverged at round {c.round}"
        )
    assert host_state.last_committed == tpu_state.last_committed
    assert [o.consensus_index for o in host_seq] == [o.consensus_index for o in dev_seq]
    return host_seq


def test_equivalence_optimal_dag():
    seq = _run_both(size=4, rounds=12, failure=0.0, seed=0)
    assert len(seq) > 30


def test_equivalence_lossy_dags():
    for seed in range(5):
        _run_both(size=4, rounds=25, failure=0.3, seed=seed)


def test_equivalence_larger_committee():
    _run_both(size=10, rounds=15, failure=0.15, seed=3)


def test_equivalence_weighted_leader():
    # default (stake-weighted) leader election on both sides
    _run_both(size=7, rounds=20, failure=0.2, seed=1, leader_fn=None)


def test_equivalence_small_window_slides():
    # Window smaller than the run length forces sliding + GC drops.
    seq = _run_both(size=4, rounds=60, failure=0.0, seed=0, gc=10, window=24)
    assert len(seq) > 200


def test_equivalence_tusk_optimal_and_lossy():
    """TpuTusk reproduces the host Tusk engine bit-for-bit (the asynchronous
    commit rule: leader two rounds below the wait round)."""
    from narwhal_tpu.consensus import Tusk
    from narwhal_tpu.tpu.dag_kernels import TpuTusk

    seq = _run_both(
        size=4, rounds=14, failure=0.0, seed=0, host_cls=Tusk, dev_cls=TpuTusk
    )
    assert len(seq) > 20
    for seed in range(3):
        _run_both(
            size=4, rounds=25, failure=0.3, seed=seed, host_cls=Tusk, dev_cls=TpuTusk
        )
    _run_both(
        size=7, rounds=20, failure=0.15, seed=2,
        leader_fn=None, host_cls=Tusk, dev_cls=TpuTusk,
    )


def _auth_mesh(auth, data=1):
    """A CPU device mesh with an 'auth' axis (and optionally a leading
    'data' axis) for the production engine's sharded dispatch."""
    import jax
    from jax.sharding import Mesh

    cpus = jax.devices("cpu")
    need = auth * data
    if len(cpus) < need:
        pytest.skip(f"need {need} cpu devices")
    if data > 1:
        return Mesh(np.array(cpus[:need]).reshape(data, auth), ("data", "auth"))
    return Mesh(np.array(cpus[:auth]), ("auth",))


def test_equivalence_mesh_sharded():
    """The PRODUCTION TpuBullshark with a 4-device 'auth' mesh: the real
    chain_commit dispatch shards the committee axis and must stay
    bit-for-bit equivalent to the host engine (VERDICT r2 #2)."""
    _run_both(size=4, rounds=20, failure=0.2, seed=0,
              dev_kwargs={"mesh": _auth_mesh(4)})


def test_equivalence_mesh_padded_committee():
    """Committee size (7) not divisible by the auth axis (2): the window
    pads the committee axis with absent slots; commits are unchanged."""
    _run_both(size=7, rounds=15, failure=0.15, seed=1, leader_fn=None,
              dev_kwargs={"mesh": _auth_mesh(2)})


def test_equivalence_mesh_two_axis():
    """A 2-axis (data x auth) mesh — the dryrun_multichip layout — behind
    the production engine: specs name only 'auth', 'data' is replicated."""
    _run_both(size=4, rounds=20, failure=0.3, seed=3,
              dev_kwargs={"mesh": _auth_mesh(2, data=4)})


def test_equivalence_mesh_tusk():
    from narwhal_tpu.consensus import Tusk
    from narwhal_tpu.tpu.dag_kernels import TpuTusk

    _run_both(size=4, rounds=20, failure=0.3, seed=2, host_cls=Tusk,
              dev_cls=TpuTusk, dev_kwargs={"mesh": _auth_mesh(2)})


def test_mesh_window_slides_and_grows():
    """Sliding + growth still work when the dispatch is mesh-sharded (the
    doubled W recompiles the sharded jit)."""
    _run_both(size=4, rounds=60, failure=0.0, seed=0, gc=10, window=24,
              dev_kwargs={"mesh": _auth_mesh(4)})


def test_window_grows_when_no_commits():
    # No leader ever present => no commits => window must grow, not slide.
    f = CommitteeFixture(size=4)
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    keys = f.committee.authority_keys()[1:]
    certs, _ = make_certificates(f.committee, 1, 40, genesis, keys=keys)
    state = ConsensusState(Certificate.genesis(f.committee))
    dev = TpuBullshark(f.committee, None, gc_depth=10, leader_fn=fixed_leader, window=16)
    for c in certs:
        assert dev.process_certificate(state, 0, c) == []
    assert dev.win.W >= 40


def test_reach_mask_simple_chain():
    # Hand-built 3-round window over 2 authorities:
    # (2,0) -> (1,1) -> (0,0); (1,0) unlinked.
    import jax.numpy as jnp

    parent = np.zeros((3, 2, 2), np.uint8)
    present = np.ones((3, 2), np.uint8)
    parent[2, 0, 1] = 1  # (2,0) links (1,1)
    parent[1, 1, 0] = 1  # (1,1) links (0,0)
    onehot = np.array([1, 0], np.uint8)
    mask = np.asarray(
        reach_mask(jnp.asarray(parent), jnp.asarray(present), jnp.int32(2), jnp.asarray(onehot))
    )
    expected = np.array([[1, 0], [0, 1], [1, 0]], bool)
    assert (mask == expected).all()

    # Committed relay blocks propagation: mark (1,1) committed.
    unc = present.copy()
    unc[1, 1] = 0
    mask2 = np.asarray(
        reach_mask(jnp.asarray(parent), jnp.asarray(unc), jnp.int32(2), jnp.asarray(onehot))
    )
    expected2 = np.array([[0, 0], [0, 0], [1, 0]], bool)
    assert (mask2 == expected2).all()


def test_leader_support_kernel():
    import jax.numpy as jnp

    parent = np.zeros((2, 3, 3), np.uint8)
    present = np.ones((2, 3), np.uint8)
    stakes = np.array([5, 7, 11], np.int32)
    parent[1, 0, 2] = 1  # authority 0 at round 1 links leader (0, 2)
    parent[1, 2, 2] = 1  # authority 2 links it too
    got = int(
        leader_support(
            jnp.asarray(parent), jnp.asarray(present), jnp.asarray(stakes),
            jnp.int32(1), jnp.int32(2),
        )
    )
    assert got == 16  # 5 + 11


def test_window_growth_is_precompiled():
    """_grow() doubles W mid-stream exactly when the node is behind; the
    engine must keep the doubled shape compiled AHEAD of need (VERDICT r2
    weak #7). We assert the prewarm covers the next size before growth and
    that the first post-growth dispatch completes without a cold-compile
    stall."""
    import time

    f = CommitteeFixture(size=4)
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    # No leader present => no commits => the window must grow past 16.
    keys = f.committee.authority_keys()[1:]
    certs, _ = make_certificates(f.committee, 1, 40, genesis, keys=keys)
    state = ConsensusState(Certificate.genesis(f.committee))
    dev = TpuBullshark(f.committee, None, gc_depth=10, leader_fn=fixed_leader,
                       window=16, prewarm=True)
    assert (32, dev.win.N, 0) in dev._warmed  # next size queued at init
    for c in certs:
        dev.process_certificate(state, 0, c)
    assert dev.win.W >= 40
    # Every size the window reached had been queued ahead of need.
    assert (dev.win.W * 2, dev.win.N, 0) in dev._warmed
    for t in dev._prewarm_threads:
        t.join(timeout=180.0)
        assert not t.is_alive()
    # A commit at the grown window size now dispatches from the warm cache:
    # well under any cold-compile time even on this host.
    from narwhal_tpu.fixtures import mock_certificate

    lead = mock_certificate(f.committee, f.committee.authority_keys()[0], 40, set())
    sup_parent = {lead.digest}
    sup = mock_certificate(
        f.committee, f.committee.authority_keys()[1], 41, sup_parent
    )
    dev.win.insert(lead, 0)
    t0 = time.monotonic()
    dev.process_certificate(state, 0, sup)
    # Generous bound: proves "no cold multi-minute compile", robust to
    # parallel load on a 1-core CI host.
    assert time.monotonic() - t0 < 30.0, "post-growth dispatch stalled"


def test_process_batch_matches_sequential():
    """The fused pipeline's engine half: feeding a causally ordered stream
    through process_batch (arbitrary chunking) yields the IDENTICAL output
    sequence to per-certificate calls — content, order and consensus
    indexes — including windows with losses and multi-leader chains."""
    f = CommitteeFixture(size=4)
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    certs, _ = make_certificates(
        f.committee, 1, 25, genesis,
        failure_probability=0.2, rng=random.Random(4),
    )
    seq_state = ConsensusState(Certificate.genesis(f.committee))
    bat_state = ConsensusState(Certificate.genesis(f.committee))
    seq_eng = TpuBullshark(f.committee, NodeStorage(None).consensus_store, GC,
                           leader_fn=fixed_leader)
    bat_eng = TpuBullshark(f.committee, NodeStorage(None).consensus_store, GC,
                           leader_fn=fixed_leader)
    seq_out = []
    i = 0
    for c in certs:
        outs = seq_eng.process_certificate(seq_state, i, c)
        i += len(outs)
        seq_out.extend(outs)
    bat_out = []
    j = 0
    for lo in range(0, len(certs), 7):  # chunking unaligned with rounds
        outs = bat_eng.process_batch(bat_state, j, certs[lo:lo + 7])
        j += len(outs)
        bat_out.extend(outs)
    assert [o.certificate.digest for o in seq_out] == [
        o.certificate.digest for o in bat_out
    ]
    assert [o.consensus_index for o in seq_out] == [
        o.consensus_index for o in bat_out
    ]
    assert seq_state.last_committed == bat_state.last_committed
    assert len(seq_out) > 10


def test_process_batch_async_matches_sequential(run):
    """The runner's burst path (process_batch_async) is output-identical."""
    f = CommitteeFixture(size=4)
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    certs, _ = make_certificates(
        f.committee, 1, 12, genesis, failure_probability=0.0,
        rng=random.Random(0),
    )
    seq_state = ConsensusState(Certificate.genesis(f.committee))
    bat_state = ConsensusState(Certificate.genesis(f.committee))
    seq_eng = TpuBullshark(f.committee, None, GC, leader_fn=fixed_leader)
    bat_eng = TpuBullshark(f.committee, None, GC, leader_fn=fixed_leader)
    seq_out = []
    i = 0
    for c in certs:
        outs = seq_eng.process_certificate(seq_state, i, c)
        i += len(outs)
        seq_out.extend(outs)

    async def batched():
        return await bat_eng.process_batch_async(bat_state, 0, list(certs))

    bat_out = run(batched(), timeout=120.0)
    assert [o.certificate.digest for o in seq_out] == [
        o.certificate.digest for o in bat_out
    ]


def test_mesh_growth_rederives_sharded_dispatch():
    """ISSUE 10 satellite: after _grow() doubles W, a MESHED engine must
    re-derive its dispatch from the kernel registry — the same process-
    wide 'auth'-sharded program — rather than a fresh unsharded jit that
    would silently run replicated layouts."""
    from narwhal_tpu.tpu import kernel_registry
    from narwhal_tpu.tpu.dag_kernels import chain_commit

    mesh = _auth_mesh(2)
    f = CommitteeFixture(size=4)
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    keys = f.committee.authority_keys()[1:]  # no leader => growth, not slide
    certs, _ = make_certificates(f.committee, 1, 40, genesis, keys=keys)
    state = ConsensusState(Certificate.genesis(f.committee))
    dev = TpuBullshark(f.committee, None, gc_depth=10, leader_fn=fixed_leader,
                       window=16, mesh=mesh)
    before = dev._chain_commit
    for c in certs:
        assert dev.process_certificate(state, 0, c) == []
    assert dev.win.W >= 40  # grew (twice)
    assert dev._dispatch_W == dev.win.W
    # Still the registry's sharded wrapper for THIS mesh — not a fresh
    # unsharded trace, and not a stale per-shape object.
    from jax.sharding import PartitionSpec as P

    expected = kernel_registry.sharded(
        chain_commit, mesh,
        in_specs=(
            P(None, None, "auth"), P(None, "auth"), None, P("auth"),
            None, None, P(None, None),
        ),
        out_specs=P(None, None, "auth"),
    )
    assert dev._chain_commit is expected
    assert expected is before  # same mesh -> same program across growth
    assert dev._chain_commit is not chain_commit
    # And the grown window still commits correctly through the mesh.
    from narwhal_tpu.fixtures import mock_certificate

    lead = mock_certificate(f.committee, f.committee.authority_keys()[0], 40, set())
    assert dev.process_certificate(state, 0, lead) == []
    outs = []
    for sup_key in f.committee.authority_keys()[1:3]:  # f+1 = 2 supporters
        sup = mock_certificate(f.committee, sup_key, 41, {lead.digest})
        outs = dev.process_certificate(state, 0, sup)
        if outs:
            break
    assert outs and outs[-1].certificate.digest == lead.digest
