"""Multi-worker validators (W>1): the payload-plane sharding contract.

A validator grows payload bandwidth by adding worker lanes — W parallel
batch-maker -> quorum-waiter -> primary-connector pipelines feeding one
primary. These tests pin the two properties millions-of-users sharding
depends on: transactions sharded across a validator's W lanes commit exactly
once (no lane duplicates or drops another lane's traffic), and losing a
worker mid-quorum neither stalls header production nor breaks liveness
(a digest only ever reaches the primary AFTER its batch reached a 2f+1
quorum of peer lanes, so a dead worker leaves no dangling payload refs)."""

import asyncio

from narwhal_tpu.cluster import Cluster
from narwhal_tpu.config import Parameters
from narwhal_tpu.messages import SubmitTransactionStreamMsg
from narwhal_tpu.network import NetworkClient


def _tx(lane: int, i: int, size: int = 64) -> bytes:
    body = b"\x01" + lane.to_bytes(4, "big") + i.to_bytes(4, "big")
    return body.ljust(size, b"\xab")


def test_multiworker_exactly_once(run):
    """Distinct transactions sharded across W=4 lanes of one validator all
    commit exactly once — none lost to a lane, none duplicated across
    worker batches — and every node executes the same stream."""

    async def scenario():
        cluster = Cluster(size=4, workers=4)
        await cluster.start()
        client = NetworkClient()
        try:
            await cluster.assert_progress(commit_threshold=1, timeout=30.0)
            expected = set()
            lanes = [
                cluster.authorities[0].worker_transactions_address(w)
                for w in range(4)
            ]
            for lane, address in enumerate(lanes):
                txs = tuple(_tx(lane, i) for i in range(8))
                expected.update(txs)
                await client.request(
                    address, SubmitTransactionStreamMsg(txs), timeout=10.0
                )

            executed: list[dict[bytes, int]] = [dict(), dict()]

            async def drain(node: int) -> None:
                ch = cluster.authorities[node].primary.tx_execution_output
                while True:
                    _, tx = await ch.recv()
                    tx = bytes(tx)
                    executed[node][tx] = executed[node].get(tx, 0) + 1

            drains = [asyncio.ensure_future(drain(i)) for i in range(2)]
            deadline = asyncio.get_event_loop().time() + 60.0
            while (
                not expected.issubset(executed[0])
                and asyncio.get_event_loop().time() < deadline
            ):
                await asyncio.sleep(0.2)
            # A couple more rounds so straggling duplicates (if any) land.
            await asyncio.sleep(1.0)
            for d in drains:
                d.cancel()

            missing = expected - set(executed[0])
            assert not missing, f"{len(missing)} sharded txs never committed"
            for node in range(2):
                dupes = {
                    t: n
                    for t, n in executed[node].items()
                    if t in expected and n != 1
                }
                assert not dupes, f"node {node} executed txs more than once: {len(dupes)}"
            # Both observed nodes agree on exactly the injected set.
            assert expected.issubset(set(executed[1]))
        finally:
            client.close()
            await cluster.shutdown()

    run(scenario(), timeout=120.0)


def test_worker_loss_mid_quorum(run):
    """Kill 1 of 4 workers at one validator mid-run, under live sharded
    traffic: the primary keeps producing headers that certify, committee
    liveness holds, and traffic on the surviving 3 lanes still commits."""

    async def scenario():
        cluster = Cluster(size=4, workers=4)
        await cluster.start()
        client = NetworkClient()
        try:
            await cluster.assert_progress(commit_threshold=1, timeout=30.0)
            a0 = cluster.authorities[0]
            lanes = [a0.worker_transactions_address(w) for w in range(4)]

            async def inject(lane: int, address: str, start: int, count: int):
                txs = tuple(_tx(lane, i) for i in range(start, start + count))
                try:
                    await client.request(
                        address, SubmitTransactionStreamMsg(txs), timeout=10.0
                    )
                except Exception:
                    return ()  # a dying lane may refuse; that's the point
                return txs

            # Traffic on all 4 lanes, then kill lane 2 mid-run.
            for lane, address in enumerate(lanes):
                await inject(lane, address, 0, 4)
            certs_before = a0.metric("primary_certificates_created")
            committed_before = a0.metric("consensus_last_committed_round")

            await a0.stop_worker(2)

            survivors = {}
            for lane, address in enumerate(lanes):
                if lane == 2:
                    continue
                survivors[lane] = await inject(lane, address, 100, 4)

            # Liveness: commits keep advancing on every node.
            await cluster.assert_progress(
                commit_threshold=int(committed_before) + 3, timeout=60.0
            )
            # Our headers still certify after the loss (header production
            # never stalled on the dead lane).
            deadline = asyncio.get_event_loop().time() + 30.0
            while (
                a0.metric("primary_certificates_created") <= certs_before
                and asyncio.get_event_loop().time() < deadline
            ):
                await asyncio.sleep(0.2)
            assert a0.metric("primary_certificates_created") > certs_before

            # Surviving lanes' post-kill traffic commits.
            expected = {t for txs in survivors.values() for t in txs}
            assert expected, "survivor lanes refused all post-kill traffic"
            seen = set()
            ch = a0.primary.tx_execution_output

            async def drain() -> None:
                while True:
                    _, tx = await ch.recv()
                    seen.add(bytes(tx))

            d = asyncio.ensure_future(drain())
            deadline = asyncio.get_event_loop().time() + 60.0
            while (
                not expected.issubset(seen)
                and asyncio.get_event_loop().time() < deadline
            ):
                await asyncio.sleep(0.2)
            d.cancel()
            missing = expected - seen
            assert not missing, f"{len(missing)} survivor-lane txs never committed"
        finally:
            client.close()
            await cluster.shutdown()

    run(scenario(), timeout=180.0)
