"""Connection-pool tests — ONE multiplexed authenticated stream per peer
pair (network/pool.py): lane dispatch, reconnect-resume after the shared
socket dies, receiver-side exactly-once across a retry, and round-robin
lane fairness under a saturated bulk lane. Real loopback sockets."""

import asyncio
import time

import pytest

from narwhal_tpu.config import Authority, Committee
from narwhal_tpu.crypto import KeyPair
from narwhal_tpu.messages import (
    Ack,
    WorkerBatchMsg,
    WorkerBatchRequest,
    WorkerBatchResponse,
)
from narwhal_tpu.network import (
    LANE_PRIMARY,
    LanePool,
    RpcError,
    RpcServer,
    worker_lane,
)
from narwhal_tpu.network.auth import Credentials
from narwhal_tpu.network.rpc import ALLOW_ANY

_DIGEST = b"d" * 32


async def _make_pair(passive_delay: float = 0.0, linger: float = 0.01):
    """Two co-hosted nodes, each an authenticated pooled listener at its
    primary address. Returns ([(pool, primary_server, network_kp)] * 2,
    committee-holder). Lane 0 is registered; worker lanes are per-test."""
    holder = {}
    nodes = []
    for _ in range(2):
        auth_kp = KeyPair.generate()
        net_kp = KeyPair.generate()
        credentials = Credentials(net_kp, lambda addr: None)
        pool = LanePool(
            net_kp.public,
            credentials,
            lambda: holder["committee"],
            passive_dial_delay=passive_delay,
            linger=linger,
        )
        server = RpcServer(auth_keypair=net_kp, pool=pool)
        nodes.append((auth_kp, net_kp, pool, server))
    authorities = {}
    for auth_kp, net_kp, pool, server in nodes:
        port = await server.start("127.0.0.1", 0)
        pool.register_lane(LANE_PRIMARY, server)
        authorities[auth_kp.public] = Authority(
            stake=1,
            primary_address=f"127.0.0.1:{port}",
            network_key=net_kp.public,
        )
    holder["committee"] = Committee(authorities)
    return nodes, holder


async def _teardown(nodes):
    for _, _, pool, server in nodes:
        pool.close()
        await server.stop()


def test_shared_socket_death_every_lane_resumes(run):
    """Kill the one pooled socket mid-traffic: the in-flight request fails
    into the caller's retry path, and the next link_for() redials — after
    which BOTH the primary lane and the worker lane work again, in both
    directions, without the pool ever holding two live links."""

    async def scenario():
        nodes, _holder = await _make_pair()
        (_, a_net, pool_a, srv_a), (_, b_net, pool_b, srv_b) = nodes
        hits = {"primary": 0, "worker": 0, "reverse": 0}
        stall = asyncio.Event()

        async def on_req(msg, peer):
            hits["primary"] += 1
            if msg.digests[0] == b"s" * 32:
                await stall.wait()
            return WorkerBatchResponse((b"p",))

        async def on_batch(msg, peer):
            hits["worker"] += 1
            return None

        async def on_reverse(msg, peer):
            hits["reverse"] += 1
            return WorkerBatchResponse((b"r",))

        srv_b.route(WorkerBatchRequest, on_req, allow=ALLOW_ANY)
        worker_srv = RpcServer(auth_keypair=b_net)
        worker_srv.route(WorkerBatchMsg, on_batch, allow=ALLOW_ANY)
        pool_b.register_lane(worker_lane(0), worker_srv)
        srv_a.route(WorkerBatchRequest, on_reverse, allow=ALLOW_ANY)

        link = await pool_a.link_for(b_net.public)
        resp = await link.request(WorkerBatchRequest((_DIGEST,)), LANE_PRIMARY)
        assert isinstance(resp, WorkerBatchResponse)
        assert isinstance(
            await link.request(WorkerBatchMsg(b"x"), worker_lane(0)), Ack
        )

        # Mid-traffic: a request is in flight (stalled in B's handler) when
        # the peer resets the shared socket under it.
        inflight = asyncio.ensure_future(
            link.request(WorkerBatchRequest((b"s" * 32,)), LANE_PRIMARY, timeout=5.0)
        )
        await asyncio.sleep(0.1)
        pool_b._links[a_net.public].close()
        with pytest.raises(RpcError):
            await inflight
        stall.set()
        assert link.closed

        # Every lane resumes over one fresh dial...
        link2 = await pool_a.link_for(b_net.public)
        assert link2 is not link
        resp = await link2.request(WorkerBatchRequest((_DIGEST,)), LANE_PRIMARY)
        assert isinstance(resp, WorkerBatchResponse)
        assert isinstance(
            await link2.request(WorkerBatchMsg(b"y"), worker_lane(0)), Ack
        )
        assert hits["worker"] == 2
        # ...and the REVERSE direction rides the same adopted connection:
        # B reaches A without ever dialing.
        link_b = await pool_b.link_for(a_net.public)
        resp = await link_b.request(WorkerBatchRequest((_DIGEST,)), LANE_PRIMARY)
        assert isinstance(resp, WorkerBatchResponse)
        assert hits["reverse"] == 1
        # One connection per peer pair at any moment, before and after.
        assert pool_a.peak_links == 1 and pool_b.peak_links == 1
        await _teardown(nodes)

    run(scenario())


def test_retry_after_reconnect_exactly_once_at_receiver(run):
    """A request retried across a reconnect is delivered exactly once from
    the receiver's perspective: the duplicate body short-circuits into the
    route's dedup bookkeeping handler (acked, counted) and the full
    handler's side effect runs once."""

    async def scenario():
        nodes, _holder = await _make_pair()
        (_, _a_net, pool_a, _srv_a), (_, b_net, _pool_b, srv_b) = nodes
        effects = []
        dup_acks = {"n": 0}

        async def on_batch(msg, peer):
            effects.append(peer.key)
            return None

        async def on_dup(msg, peer):
            dup_acks["n"] += 1
            return None  # still an Ack: the sender's retry is satisfied

        srv_b.route(WorkerBatchMsg, on_batch, allow=ALLOW_ANY, dedup=on_dup)

        msg = WorkerBatchMsg(b"the-one-batch")
        link = await pool_a.link_for(b_net.public)
        ack1 = await link.request(msg, LANE_PRIMARY)
        # The connection dies before the caller consumes the ack; the retry
        # layer re-sends the SAME bytes over a fresh link.
        link.close()
        link2 = await pool_a.link_for(b_net.public)
        ack2 = await link2.request(msg, LANE_PRIMARY)

        assert isinstance(ack1, Ack) and isinstance(ack2, Ack)
        assert len(effects) == 1  # the side effect happened exactly once
        assert dup_acks["n"] == 1  # the duplicate took the cheap path
        await _teardown(nodes)

    run(scenario())


def test_vote_lane_bounded_under_saturated_batch_lane(run):
    """Round-robin lane interleaving: a vote-lane request enqueued behind a
    deep batch-lane backlog on the SAME connection departs in the first
    drain pass — the receiver sees it ahead of nearly all the backlog, and
    its latency stays bounded while megabytes of bulk frames are queued."""

    async def scenario():
        nodes, _holder = await _make_pair()
        (_, _a_net, pool_a, _srv_a), (_, b_net, pool_b, srv_b) = nodes
        order = []

        async def on_vote(msg, peer):
            order.append("vote")
            return WorkerBatchResponse((b"v",))

        async def on_batch(msg, peer):
            order.append("batch")
            return None

        srv_b.route(WorkerBatchRequest, on_vote, allow=ALLOW_ANY)
        worker_srv = RpcServer(auth_keypair=b_net)
        worker_srv.route(WorkerBatchMsg, on_batch, allow=ALLOW_ANY)
        pool_b.register_lane(worker_lane(0), worker_srv)

        link = await pool_a.link_for(b_net.public)
        # Saturate the batch lane: 32 x 64KiB enqueued in one event-loop
        # tick (oneway never yields), so the drainer faces a ~2MiB backlog
        # the moment the vote shows up on lane 0.
        blob = bytes(64 * 1024)
        for _ in range(32):
            await link.oneway(WorkerBatchMsg(blob), worker_lane(0))
        t0 = time.monotonic()
        resp = await link.request(
            WorkerBatchRequest((_DIGEST,)), LANE_PRIMARY, timeout=5.0
        )
        vote_rtt = time.monotonic() - t0
        assert isinstance(resp, WorkerBatchResponse)
        # Wait for the backlog to finish arriving, then check placement.
        for _ in range(100):
            if order.count("batch") == 32:
                break
            await asyncio.sleep(0.05)
        assert order.count("batch") == 32
        # FIFO would put the vote at index 32; interleaving puts it in the
        # first pass (a frame or two of slack for scheduling).
        assert order.index("vote") <= 4, order
        assert vote_rtt < 2.0
        await _teardown(nodes)

    run(scenario())
