"""Fanout-tree dissemination + delta-encoded headers (the wire diet).

Covers the deterministic relay-tree construction, the delta header codec's
encode/decode/resync contract, the per-link wire-accounting metrics, and —
the acceptance fixture — dissemination equivalence: every correct node
certifies the same per-round header sets under fanout-tree relay as under
direct broadcast, including with one relay node crashed (exercising the
origin's direct-send fallback)."""

import asyncio

from narwhal_tpu.cluster import Cluster
from narwhal_tpu.config import Parameters
from narwhal_tpu.fixtures import CommitteeFixture, make_signed_certificates
from narwhal_tpu.primary.delta import HeaderDeltaCodec, encode_announcement
from narwhal_tpu.primary.fanout import relay_children, relay_order
from narwhal_tpu.messages import DeltaHeaderMsg, HeaderMsg
from narwhal_tpu.types import Certificate


# ---------------------------------------------------------------------------
# Tree construction
# ---------------------------------------------------------------------------


def test_relay_order_deterministic_and_rotating():
    f = CommitteeFixture(size=10)
    root = f.authorities[0].public
    a = relay_order(f.committee, 0, 5, root)
    b = relay_order(f.committee, 0, 5, root)
    assert a == b  # every node derives the identical tree
    assert set(a) == {x.public for x in f.authorities} - {root}
    # Seeded per round: relay positions rotate so no authority is a
    # permanent interior node (identical permutations across rounds would
    # be a 1/9! coincidence).
    rotations = {tuple(relay_order(f.committee, 0, r, root)) for r in range(8)}
    assert len(rotations) > 1
    # And per origin.
    other_root = f.authorities[1].public
    assert relay_order(f.committee, 0, 5, other_root) != a


def test_relay_children_partition_the_committee():
    """Every non-origin node appears in exactly one parent's child list —
    the tree reaches everyone exactly once, at depth >= 2 when the
    committee outgrows the fanout."""
    f = CommitteeFixture(size=9)
    committee = f.committee
    fanout = 2
    for round in (1, 2, 7):
        for origin_fx in f.authorities[:3]:
            origin = origin_fx.public
            seen: list[bytes] = []
            interior = 0
            for member_fx in f.authorities:
                kids = relay_children(
                    committee, 0, round, origin, member_fx.public, fanout
                )
                assert len(kids) <= fanout
                if member_fx.public != origin and kids:
                    interior += 1
                seen.extend(kids)
            assert sorted(seen) == sorted(
                x.public for x in f.authorities if x.public != origin
            )
            assert interior >= 1  # depth >= 2: someone besides the origin relays


def test_relay_order_is_stake_weighted():
    """Higher stake lands closer to the root on average (more relay duty
    where the resources are). Deterministic: the tickets are pure integer
    hashes of fixed seeds."""
    f = CommitteeFixture(size=6, stakes=[100, 1, 1, 1, 1, 1])
    heavy = f.authorities[0].public
    # The heavy authority may not be index 0 after canonical sorting; find
    # the staked key from the committee itself.
    heavy = max(f.committee.authorities, key=lambda pk: f.committee.stake(pk))
    root = next(pk for pk in f.committee.authority_keys() if pk != heavy)
    positions = []
    for r in range(200):
        order = relay_order(f.committee, 0, r, root)
        positions.append(order.index(heavy))
    mean_pos = sum(positions) / len(positions)
    assert mean_pos < 1.0  # ~0.08 expected at 100:1 stake; 2.0 if unweighted


# ---------------------------------------------------------------------------
# Delta header codec
# ---------------------------------------------------------------------------


def _fixture_with_round1_certs():
    f = CommitteeFixture(size=4)
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    certs, parents = make_signed_certificates(f, 1, 1, genesis)
    return f, certs, parents


def test_delta_codec_roundtrip():
    f, certs, parents = _fixture_with_round1_certs()
    sender = HeaderDeltaCodec(f.committee)
    receiver = HeaderDeltaCodec(f.committee)
    for c in certs:
        sender.note_certificate(c)
        receiver.note_certificate(c)
    payload = {b"\x11" * 32: 0, b"\x22" * 32: 0}
    header = f.header(author=0, round=2, payload=payload, parents=parents)
    msg = sender.encode_header(header)
    assert isinstance(msg, DeltaHeaderMsg)
    # The wire form carries 2-byte parent refs, not 32-byte digests.
    assert msg.parent_indices and len(msg.parent_indices) == len(parents)
    rebuilt = receiver.decode_header(msg)
    assert rebuilt is not None
    assert rebuilt.digest == header.digest
    assert rebuilt.to_bytes() == header.to_bytes()  # byte-exact reconstruction
    # Signature survives: the normal sanitize path verifies it.
    rebuilt.verify(f.committee, f.worker_cache)


def test_delta_codec_missing_parent_and_mismatch():
    f, certs, parents = _fixture_with_round1_certs()
    sender = HeaderDeltaCodec(f.committee)
    for c in certs:
        sender.note_certificate(c)
    header = f.header(author=0, round=2, parents=parents)
    msg = sender.encode_header(header)

    # A receiver that never saw the round-1 certificates cannot reconstruct
    # -> None -> the caller resyncs the full header.
    behind = HeaderDeltaCodec(f.committee)
    assert behind.decode_header(msg) is None

    # A tampered digest (or a stale index) must not produce a wrong header.
    receiver = HeaderDeltaCodec(f.committee)
    for c in certs:
        receiver.note_certificate(c)
    from dataclasses import replace

    forged = replace(msg, header_digest=b"\x99" * 32)
    assert receiver.decode_header(forged) is None


def test_delta_encode_falls_back_to_full_header():
    """encode_announcement never fails: parents missing from the index =>
    the self-describing full HeaderMsg goes out instead."""
    f, certs, parents = _fixture_with_round1_certs()
    codec = HeaderDeltaCodec(f.committee)  # round-1 certs NOT noted
    header = f.header(author=0, round=2, parents=parents)
    assert codec.encode_header(header) is None
    msg = encode_announcement(codec, header, "delta")
    assert isinstance(msg, HeaderMsg)
    # Genesis is seeded, so round-1 headers delta-encode from boot.
    h1 = f.header(author=0, round=1)
    assert isinstance(encode_announcement(codec, h1, "delta"), DeltaHeaderMsg)
    # And the "full" wire form always sends the full header.
    assert isinstance(encode_announcement(codec, h1, "full"), HeaderMsg)


# ---------------------------------------------------------------------------
# Cluster-level: equivalence + fallback + wire metrics
# ---------------------------------------------------------------------------


def _certified_by_round(cluster, upto_round):
    """Per-node {round: sorted certificate digests} for rounds <= upto."""
    out = []
    for a in cluster.authorities:
        if a.primary is None:
            continue
        certs = a.primary.storage.certificate_store.after_round(0)
        by_round = {}
        for c in certs:
            if 0 < c.round <= upto_round:
                by_round.setdefault(c.round, []).append(c.digest)
        out.append({r: sorted(ds) for r, ds in by_round.items()})
    return out


async def _drive(relay_fanout, size=7, threshold=3, stop_index=None):
    """Run a committee to `threshold` committed rounds; optionally crash one
    node midway. Returns (per-node certified sets, fallback send total)."""
    cluster = Cluster(
        size=size,
        parameters=Parameters(
            max_header_delay=0.1,
            max_batch_delay=0.1,
            relay_fanout=relay_fanout,
        ),
    )
    await cluster.start()
    try:
        await cluster.assert_progress(commit_threshold=1, timeout=30.0)
        if stop_index is not None:
            await cluster.stop_node(stop_index)
        await cluster.assert_progress(
            expected_nodes=size - (1 if stop_index is not None else 0),
            commit_threshold=threshold,
            timeout=60.0,
        )

        def fallback_total() -> float:
            return sum(
                a.metric("primary_relay_fallback_sends")
                for a in cluster.authorities
                if a.primary is not None
            )

        if stop_index is not None:
            # The dead node never acks, so every origin's fallback timer
            # (relay_fallback_timeout) direct-sends to it — but those
            # timers may not have FIRED yet when progress lands; give them
            # a few timeout periods.
            deadline = asyncio.get_event_loop().time() + 15.0
            while (
                fallback_total() == 0
                and asyncio.get_event_loop().time() < deadline
            ):
                await asyncio.sleep(0.2)
        return _certified_by_round(cluster, threshold), fallback_total()
    finally:
        await cluster.shutdown()


def _assert_all_nodes_agree(per_node, min_rounds):
    """Every correct node certified the SAME header set at every compared
    round (committed rounds are causally complete, so stores must agree)."""
    reference = per_node[0]
    compared = 0
    for r in sorted(reference):
        if all(r in node for node in per_node[1:]):
            for node in per_node[1:]:
                assert node[r] == reference[r], f"round {r} certificate sets differ"
            compared += 1
    assert compared >= min_rounds


def test_dissemination_equivalence_relay_vs_direct(run):
    """The acceptance fixture: under fanout-tree relay every correct node
    certifies the same headers as under direct broadcast — the relay plane
    changes who carries the bytes, never what gets certified."""

    async def scenario():
        relayed, _ = await _drive(relay_fanout=2)
        direct, _ = await _drive(relay_fanout=0)
        _assert_all_nodes_agree(relayed, min_rounds=3)
        _assert_all_nodes_agree(direct, min_rounds=3)

    run(scenario(), timeout=240.0)


def test_dissemination_survives_crashed_relay(run):
    """Crash one node mid-run (with fanout=2 at N=7, every node is an
    interior relay in a rotating share of trees): liveness holds, the
    surviving nodes still converge on identical certificate sets, and the
    origins' direct-send fallback actually fired."""

    async def scenario():
        per_node, fallback = await _drive(
            relay_fanout=2, threshold=4, stop_index=3
        )
        assert len(per_node) == 6
        _assert_all_nodes_agree(per_node, min_rounds=3)
        # The crashed node was somebody's relay: un-acked peers got the
        # message via the fallback path.
        assert fallback > 0

    run(scenario(), timeout=240.0)


def test_wire_accounting_metrics_consistent(run):
    """Satellite: a 4-node round reports nonzero, consistent per-link wire
    totals — every primary sent and received announcement/vote bytes, and
    committee-wide receives never exceed committee-wide sends for the
    primary-to-primary types (a frame must be written before it is read)."""

    async def scenario():
        cluster = Cluster(size=4)
        await cluster.start()
        try:
            await cluster.assert_progress(commit_threshold=2, timeout=30.0)

            def by_type(a, name):
                m = a.primary.registry.get(name)
                return {k[0]: c.value for k, c in m._children.items()} if m else {}

            sent = [by_type(a, "wire_bytes_sent_total") for a in cluster.authorities]
            recv = [
                by_type(a, "wire_bytes_received_total")
                for a in cluster.authorities
            ]
            # Nonzero on every node: headers go out (delta wire form by
            # default), votes flow both ways (slim Vote2Msg by default,
            # full VoteMsg still accepted).
            for s, r in zip(sent, recv):
                assert s.get("DeltaHeaderMsg", 0) + s.get("HeaderMsg", 0) > 0
                assert s.get("VoteMsg", 0) + s.get("Vote2Msg", 0) > 0
                assert r.get("VoteMsg", 0) + r.get("Vote2Msg", 0) > 0
            # Consistency: closed committee — for primary-plane types the
            # aggregate received bytes cannot exceed aggregate sent bytes.
            for msg_type in ("DeltaHeaderMsg", "HeaderMsg", "VoteMsg", "Vote2Msg"):
                total_sent = sum(s.get(msg_type, 0) for s in sent)
                total_recv = sum(r.get(msg_type, 0) for r in recv)
                assert total_recv <= total_sent
            # The per-round egress gauge is live on every node.
            for a in cluster.authorities:
                assert a.metric("primary_round_egress_bytes") > 0
        finally:
            await cluster.shutdown()

    run(scenario(), timeout=120.0)


def test_relay2_slim_codec_roundtrips_byte_exact():
    """encode_relay2/decode_relay2: the slim bodies reconstitute the EXACT
    fat announcement (bitmap signers/parents, envelope-deduped fields), the
    generic kind carries anything else verbatim, and out-of-range values
    refuse to encode slim (caller falls back to the legacy envelope)."""
    from narwhal_tpu.fixtures import CommitteeFixture
    from narwhal_tpu.messages import (
        CertificateRefMsg,
        DeltaHeaderMsg,
        HeaderMsg,
        Relay2Msg,
    )
    from narwhal_tpu.primary.fanout import (
        R2_CERT_REF,
        R2_DELTA_HEADER,
        R2_GENERIC,
        decode_relay2,
        encode_relay2,
    )
    from narwhal_tpu.types import Certificate, Header, Vote

    fx = CommitteeFixture(size=7)
    committee = fx.committee
    origin = fx.authorities[2]
    h = Header.build(
        origin.public, 5, 0, {b"\x0a" * 32: 3},
        frozenset(c.digest for c in Certificate.genesis(committee)),
        origin.signature_service(),
    )
    votes = [
        Vote.for_header(h, a.public, a.signature_service())
        for a in fx.authorities[:5]
    ]
    signers, sigs = zip(
        *sorted((committee.index_of(v.author), v.signature) for v in votes)
    )
    cert = Certificate.compact_from_votes(h, tuple(signers), tuple(sigs))

    ref = CertificateRefMsg.from_certificate(cert)
    slim = encode_relay2(committee, origin.public, cert.round, ref)
    assert slim is not None and slim.kind == R2_CERT_REF
    back = decode_relay2(committee, slim)
    assert back == ref
    assert back.rebuild(h).to_bytes() == cert.to_bytes()

    delta = DeltaHeaderMsg(
        origin.public, 5, 0, h.digest, tuple(h.payload.items()),
        (0, 1, 4, 6), h.signature,
    )
    slim_h = encode_relay2(committee, origin.public, 5, delta)
    assert slim_h is not None and slim_h.kind == R2_DELTA_HEADER
    assert decode_relay2(committee, slim_h) == delta

    # Anything the slim kinds cannot express rides the generic kind
    # verbatim.
    full = HeaderMsg(h)
    slim_g = encode_relay2(committee, origin.public, 5, full)
    assert slim_g is not None and slim_g.kind == R2_GENERIC
    assert decode_relay2(committee, slim_g).header.to_bytes() == h.to_bytes()

    # Out-of-slim-range rounds refuse (legacy RelayMsg covers them).
    assert encode_relay2(committee, origin.public, 1 << 33, ref) is None

    # Malformed envelopes are rejected, never mis-decoded.
    import pytest as _pytest

    bad = Relay2Msg(999, 5, 0, R2_CERT_REF, slim.body)
    with _pytest.raises(ValueError):
        decode_relay2(committee, bad)


def test_oneway_frames_dispatch_without_response(run):
    """KIND_ONEWAY: the handler runs, no response frame comes back, and the
    connection stays healthy for normal request/response traffic after."""
    import asyncio

    from narwhal_tpu.messages import CleanupMsg
    from narwhal_tpu.network import NetworkClient, RpcServer

    async def scenario():
        got = []
        srv = RpcServer()

        async def on_cleanup(msg, peer):
            got.append(msg.round)
            return None

        srv.route(CleanupMsg, on_cleanup)
        port = await srv.start("127.0.0.1", 0)
        client = NetworkClient()
        try:
            addr = f"127.0.0.1:{port}"
            assert await client.oneway_send(addr, CleanupMsg(7))
            for _ in range(50):
                if got:
                    break
                await asyncio.sleep(0.05)
            assert got == [7]
            # The same connection still serves acked requests.
            assert await client.unreliable_send(addr, CleanupMsg(9))
            assert sorted(got) == [7, 9]
        finally:
            client.close()
            await srv.stop()

    run(scenario(), timeout=30.0)
