"""Data model tests, mirroring /root/reference/types/src/tests/
(batch_serde.rs, certificate_tests.rs) and config tests."""

import pytest

from narwhal_tpu.codec import CodecError, Reader, Writer
from narwhal_tpu.config import Committee, Parameters, WorkerCache
from narwhal_tpu.crypto import KeyPair, batch_verify, digest256, verify
from narwhal_tpu.fixtures import CommitteeFixture, make_optimal_certificates
from narwhal_tpu.types import (
    Batch,
    Certificate,
    DagError,
    Header,
    InvalidEpoch,
    InvalidSignatureError,
    QuorumNotReached,
    Vote,
    serialized_batch_digest,
)


def test_codec_roundtrip():
    w = Writer()
    w.u8(7).u32(1234).u64(2**40).bytes(b"hello").seq([1, 2, 3], lambda w_, v: w_.u16(v))
    data = w.finish()
    r = Reader(data)
    assert r.u8() == 7
    assert r.u32() == 1234
    assert r.u64() == 2**40
    assert r.bytes() == b"hello"
    assert r.seq(lambda r_: r_.u16()) == [1, 2, 3]
    r.done()


def test_codec_truncation():
    with pytest.raises(CodecError):
        Reader(b"\x01").u32()
    with pytest.raises(CodecError):
        Reader(b"\xff\xff\xff\xff").seq(lambda r: r.u8())


def test_batch_serde_and_digest():
    b = Batch((b"tx1", b"tx2", b"a longer transaction payload"))
    wire = b.to_bytes()
    assert Batch.from_bytes(wire) == b
    # serialized digest == object digest (the zero-copy receive-path property,
    # reference types/src/tests/batch_serde.rs:88)
    assert serialized_batch_digest(wire) == b.digest
    assert b.digest != Batch((b"tx1",)).digest


def test_header_sign_verify():
    f = CommitteeFixture(size=4)
    h = f.header(author=0, round=1)
    h.verify(f.committee, f.worker_cache)
    assert Header.from_bytes(h.to_bytes()).digest == h.digest

    # wrong epoch rejected
    bad = Header(h.author, h.round, 5, h.payload, h.parents, h.signature)
    with pytest.raises(InvalidEpoch):
        bad.verify(f.committee, f.worker_cache)

    # tampered payload => signature invalid
    tampered = Header(
        h.author, h.round, h.epoch, {digest256(b"x"): 0}, h.parents, h.signature
    )
    with pytest.raises(DagError):
        tampered.verify(f.committee, f.worker_cache)


def test_vote_and_certificate():
    f = CommitteeFixture(size=4)
    h = f.header(author=0, round=1)
    votes = f.votes(h)
    assert len(votes) == 3
    for v in votes:
        v.verify(f.committee)

    cert = f.certificate(h)
    cert.verify(f.committee, f.worker_cache)
    assert Certificate.from_bytes(cert.to_bytes()).digest == cert.digest

    # quorum: 2 of 4 equal-stake signers is below 2f+1=3
    small = Certificate(h, cert.signers[:2], cert.signatures[:2])
    with pytest.raises(QuorumNotReached):
        small.verify(f.committee, f.worker_cache)

    # a flipped signature bit fails batch verification
    sigs = list(cert.signatures)
    sigs[1] = bytes([sigs[1][0] ^ 1]) + sigs[1][1:]
    forged = Certificate(h, cert.signers, tuple(sigs))
    with pytest.raises(InvalidSignatureError):
        forged.verify(f.committee, f.worker_cache)


def test_certificate_digest_independent_of_votes():
    f = CommitteeFixture(size=4)
    h = f.header(author=1, round=2, parents={c.digest for c in Certificate.genesis(f.committee)})
    full = f.certificate(h)
    partial = Certificate(h, full.signers[:3], full.signatures[:3])
    assert full.digest == partial.digest  # identity is the header


def test_genesis():
    f = CommitteeFixture(size=4)
    gen = Certificate.genesis(f.committee)
    assert len(gen) == 4
    for c in gen:
        c.verify(f.committee, f.worker_cache)  # structural check only
        assert c.is_genesis() and c.compressible()


def test_crypto_batch_verify():
    kp = KeyPair.from_seed(b"k" * 32)
    msgs = [f"msg-{i}".encode() for i in range(8)]
    items = [(kp.public, m, kp.sign(m)) for m in msgs]
    assert batch_verify(items) == [True] * 8
    bad = list(items)
    bad[3] = (kp.public, b"other", items[3][2])
    assert batch_verify(bad) == [True] * 3 + [False] + [True] * 4
    assert verify(kp.public, msgs[0], items[0][2])


def test_committee_math():
    f = CommitteeFixture(size=4)
    c = f.committee
    assert c.total_stake() == 4
    assert c.quorum_threshold() == 3  # 2f+1 with f=1
    assert c.validity_threshold() == 2  # f+1
    assert len(c.others_primaries(f.authority(0).public)) == 3
    # leader is deterministic and stake-weighted
    assert c.leader(42) == c.leader(42)
    assert c.leader(42) in c.authorities

    c10 = CommitteeFixture(size=10).committee
    assert c10.quorum_threshold() == 7
    assert c10.validity_threshold() == 4


def test_committee_weighted_leader():
    f = CommitteeFixture(size=4, stakes=[97, 1, 1, 1])
    heavy = max(f.committee.authorities, key=lambda pk: f.committee.stake(pk))
    picks = sum(f.committee.leader(s) == heavy for s in range(200))
    assert picks > 150  # ~97% expected


def test_config_json_roundtrip(tmp_path):
    f = CommitteeFixture(size=4, workers=2, base_port=9000)
    p = tmp_path / "committee.json"
    f.committee.export(str(p))
    assert Committee.import_(str(p)) == f.committee

    wp = tmp_path / "workers.json"
    f.worker_cache.export(str(wp))
    wc = WorkerCache.from_json(f.worker_cache.to_json())
    assert wc.workers == f.worker_cache.workers

    params = Parameters(batch_size=1234)
    pp = tmp_path / "parameters.json"
    params.export(str(pp))
    assert Parameters.import_(str(pp)).batch_size == 1234


def test_dag_generators():
    f = CommitteeFixture(size=4)
    genesis = {c.digest for c in Certificate.genesis(f.committee)}
    certs, parents = make_optimal_certificates(f.committee, 1, 5, genesis)
    assert len(certs) == 20
    assert len(parents) == 4
    rounds = {c.round for c in certs}
    assert rounds == {1, 2, 3, 4, 5}
    # each non-first round certificate links to all 4 previous certs
    for c in certs:
        assert len(c.header.parents) == 4


def test_compact_certificate_roundtrip_and_verify():
    """Half-aggregated certificates: same digest as the full form, wire
    round-trip, host verification accepts honest proofs and rejects
    tampered scalars/swapped nonces (types.py Certificate compact form)."""
    from narwhal_tpu.fixtures import CommitteeFixture
    from narwhal_tpu.types import Certificate, Vote

    fx = CommitteeFixture(size=4)
    h = fx.header(author=0, round=1)
    signers, sigs = [], []
    for a in fx.authorities:
        v = Vote.for_header(h, a.public, a.keypair)
        signers.append(fx.committee.index_of(a.public))
        sigs.append(v.signature)
    cc = Certificate.compact_from_votes(h, tuple(signers), tuple(sigs))
    assert cc.is_compact
    assert cc.digest == fx.certificate(h).digest  # form-independent identity
    cc.verify(fx.committee, fx.worker_cache)
    assert Certificate.from_bytes(cc.to_bytes()) == cc

    import pytest as _pytest

    from narwhal_tpu.types import InvalidSignatureError

    bad_s = Certificate(
        cc.header, cc.signers, cc.signatures,
        bytes([cc.agg_s[0] ^ 1]) + cc.agg_s[1:],
    )
    with _pytest.raises(InvalidSignatureError):
        bad_s.verify(fx.committee, fx.worker_cache)
    swapped = list(cc.signatures)
    swapped[0], swapped[1] = swapped[1], swapped[0]
    bad_r = Certificate(cc.header, cc.signers, tuple(swapped), cc.agg_s)
    with _pytest.raises(InvalidSignatureError):
        bad_r.verify(fx.committee, fx.worker_cache)


def test_compact_certificate_broadcast_bytes_at_n50():
    """The control-plane win at the north-star committee size: a compact
    certificate announcement (CertificateRefMsg — header by digest +
    half-aggregated proof) must be >=3x smaller on the wire than today's
    full-multisig CertificateMsg (VERDICT r3 item 6; the capability the
    reference's O(1) BLS certificates provide,
    /root/reference/types/src/primary.rs:386-644)."""
    import os

    from narwhal_tpu.fixtures import CommitteeFixture
    from narwhal_tpu.messages import (
        CertificateMsg,
        CertificateRefMsg,
        encode_message,
    )
    from narwhal_tpu.types import Certificate, Header, Vote

    fx = CommitteeFixture(size=50)
    committee = fx.committee
    # A realistic round-r header: 50 parent digests + some payload.
    parents = {os.urandom(32) for _ in range(50)}
    payload = {os.urandom(32): 0 for _ in range(8)}
    a0 = fx.authorities[0]
    h = Header.build(a0.public, 5, 0, payload, parents, a0.keypair)
    # Quorum of signers (2f+1 = 34 of 50).
    quorum = fx.authorities[:34]
    signers = tuple(sorted(committee.index_of(a.public) for a in quorum))
    by_index = {committee.index_of(a.public): a for a in quorum}
    sigs = tuple(
        Vote.for_header(h, by_index[i].public, by_index[i].keypair).signature
        for i in signers
    )
    full = Certificate(h, signers, sigs)
    compact = Certificate.compact_from_votes(h, signers, sigs)

    _, full_bytes = encode_message(CertificateMsg(full))
    _, ref_bytes = encode_message(
        CertificateRefMsg.from_certificate(compact)
    )
    ratio = len(full_bytes) / len(ref_bytes)
    assert ratio >= 3.0, (len(full_bytes), len(ref_bytes), ratio)
    # And the compact proof still verifies.
    compact.verify(committee, fx.worker_cache)
